"""Python side of the C inference API (csrc/capi.cc).

Reference: paddle/legacy/capi — a pure-C ABI (paddle_matrix,
paddle_gradient_machine_*) for embedding inference into C/C++ apps.  The
TPU build's engine lives in Python/JAX, so the C shim embeds CPython and
drives this bridge: byte buffers + shapes cross the ABI, numpy/JAX stays
on this side."""

import numpy as np

from . import inference as _inference
from . import fluid

_DTYPES = {0: np.float32, 1: np.int64, 2: np.int32, 3: np.float64}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


class CApiPredictor(object):
    def __init__(self, model_dir):
        config = _inference.NativeConfig(model_dir=model_dir)
        self._predictor = _inference.create_paddle_predictor(config)
        self._inputs = {}
        self._outputs = []

    def set_input(self, name, data, shape, dtype_code):
        arr = np.frombuffer(data, dtype=_DTYPES[int(dtype_code)]).reshape(
            [int(s) for s in shape])
        self._inputs[name] = arr

    def run(self):
        outs = self._predictor.run(self._inputs)
        self._outputs = [
            np.ascontiguousarray(np.asarray(t.data)) for t in outs
        ]
        self._inputs = {}
        return len(self._outputs)

    def output_count(self):
        return len(self._outputs)

    def get_output(self, i):
        arr = self._outputs[int(i)]
        code = _DTYPE_CODES.get(arr.dtype)
        if code is None:
            arr = arr.astype(np.float32)
            code = 0
        return (arr.tobytes(), list(arr.shape), code)


def create(model_dir):
    return CApiPredictor(model_dir)


class CApiTrainer(object):
    """C-side TRAINING loop (reference train/demo/demo_trainer.cc: load
    serialized startup/main ProgramDesc files, find the mean op's output
    as the loss, run the startup program, then step the train program).
    The program files are the framework.proto bytes the reference demo
    reads — full contract parity."""

    def __init__(self, model_dir):
        import os
        with open(os.path.join(model_dir, 'main_program'), 'rb') as f:
            self._main = fluid.Program.parse_from_string(f.read())
        with open(os.path.join(model_dir, 'startup_program'), 'rb') as f:
            startup = fluid.Program.parse_from_string(f.read())
        self._loss_name = None
        for op in self._main.global_block().ops:
            if op.type == 'mean':
                self._loss_name = op.output('Out')[0]
                break
        if self._loss_name is None:
            raise RuntimeError('loss (mean op) not found in main program')
        place = fluid.TPUPlace() if fluid.core.is_compiled_with_tpu() \
            else fluid.CPUPlace()
        self._scope = fluid.core.Scope()
        self._exe = fluid.Executor(place)
        with fluid.scope_guard(self._scope):
            self._exe.run(startup)
        self._inputs = {}

    def set_input(self, name, data, shape, dtype_code):
        arr = np.frombuffer(data, dtype=_DTYPES[int(dtype_code)]).reshape(
            [int(s) for s in shape])
        self._inputs[name] = arr

    def step(self):
        """One training step; returns the scalar loss."""
        with fluid.scope_guard(self._scope):
            v, = self._exe.run(self._main, feed=dict(self._inputs),
                               fetch_list=[self._loss_name])
        return float(np.asarray(v).flatten()[0])


def create_trainer(model_dir):
    return CApiTrainer(model_dir)
