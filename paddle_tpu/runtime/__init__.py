"""Native runtime bindings (C++ via ctypes).

The reference's runtime around the compute path is C++ (recordio, reader
queues, buddy allocator — SURVEY §2.1); this package binds the TPU-native
equivalents built from ``csrc/``.  If the shared library is missing it is
built on first use with the in-image toolchain; pure-Python fallbacks keep
everything working without a compiler.
"""

from .native import (lib_available, RecordIOWriter, RecordIOScanner,
                     NativeBlockingQueue, host_pool_stats)

__all__ = [
    'lib_available', 'RecordIOWriter', 'RecordIOScanner',
    'NativeBlockingQueue', 'host_pool_stats',
]
