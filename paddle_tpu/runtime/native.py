"""ctypes bindings over libpaddle_tpu_rt.so (csrc/)."""

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, 'libpaddle_tpu_rt.so')
_CSRC = os.path.normpath(os.path.join(_HERE, '..', '..', 'csrc'))

_lib = None
_lib_lock = threading.Lock()


def _try_build():
    if not os.path.isdir(_CSRC):
        return False
    try:
        subprocess.run(['make'], cwd=_CSRC, check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH):
            if not _try_build():
                return None
        # an on-disk .so from an older source tree may predate newly added
        # symbols.  Probe BEFORE dlopening the stale image into the
        # process (a dlopen'd inode cannot be reloaded, and relinking it
        # in place would corrupt the live mapping): rebuild to a temp
        # path and atomically replace, then load once
        probe = ctypes.CDLL(_SO_PATH)
        if not hasattr(probe, 'ms_create'):
            del probe  # note: the stale image stays mapped (no dlclose)
            import tempfile
            import subprocess as sp
            tmp = None
            try:
                tmp = tempfile.NamedTemporaryFile(
                    dir=os.path.dirname(_SO_PATH), suffix='.so',
                    delete=False)
                tmp.close()
                sp.run(['make', '-B', 'OUT=%s' % tmp.name], cwd=_CSRC,
                       check=True, capture_output=True, timeout=120)
                os.chmod(tmp.name, 0o755)
                os.replace(tmp.name, _SO_PATH)
            except Exception:
                if tmp is not None:
                    try:
                        os.unlink(tmp.name)
                    except OSError:
                        pass
                return None
            lib = ctypes.CDLL(_SO_PATH)
            if not hasattr(lib, 'ms_create'):
                return None
        else:
            lib = probe
        # recordio
        lib.recordio_writer_create.restype = ctypes.c_void_p
        lib.recordio_writer_create.argtypes = [ctypes.c_char_p,
                                               ctypes.c_int,
                                               ctypes.c_uint64]
        lib.recordio_writer_write.restype = ctypes.c_int
        lib.recordio_writer_write.argtypes = [ctypes.c_void_p,
                                              ctypes.c_char_p,
                                              ctypes.c_uint64]
        lib.recordio_writer_close.restype = ctypes.c_int
        lib.recordio_writer_close.argtypes = [ctypes.c_void_p]
        lib.recordio_scanner_create.restype = ctypes.c_void_p
        lib.recordio_scanner_create.argtypes = [ctypes.c_char_p]
        lib.recordio_scanner_next.restype = ctypes.c_int
        lib.recordio_scanner_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.recordio_scanner_destroy.argtypes = [ctypes.c_void_p]
        # blocking queue
        lib.bq_create.restype = ctypes.c_void_p
        lib.bq_create.argtypes = [ctypes.c_uint64]
        lib.bq_push.restype = ctypes.c_int
        lib.bq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint64]
        lib.bq_pop.restype = ctypes.c_int64
        lib.bq_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint64]
        lib.bq_size.restype = ctypes.c_uint64
        lib.bq_size.argtypes = [ctypes.c_void_p]
        lib.bq_close.argtypes = [ctypes.c_void_p]
        lib.bq_reopen.argtypes = [ctypes.c_void_p]
        lib.bq_destroy.argtypes = [ctypes.c_void_p]
        # host pool
        lib.hp_in_use.restype = ctypes.c_uint64
        lib.hp_cached.restype = ctypes.c_uint64
        lib.hp_peak.restype = ctypes.c_uint64
        # CSP channels
        lib.ch_create.restype = ctypes.c_void_p
        lib.ch_create.argtypes = [ctypes.c_uint64]
        lib.ch_destroy.argtypes = [ctypes.c_void_p]
        lib.ch_size.restype = ctypes.c_uint64
        lib.ch_size.argtypes = [ctypes.c_void_p]
        lib.ch_is_closed.restype = ctypes.c_int
        lib.ch_is_closed.argtypes = [ctypes.c_void_p]
        lib.ch_close.argtypes = [ctypes.c_void_p]
        lib.ch_send.restype = ctypes.c_int
        lib.ch_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint64]
        lib.ch_try_send.restype = ctypes.c_int
        lib.ch_try_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64]
        lib.ch_recv.restype = ctypes.c_int
        lib.ch_recv.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint64]
        lib.ch_try_recv.restype = ctypes.c_int
        lib.ch_try_recv.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64]
        # EDL master task queue
        lib.ms_create.restype = ctypes.c_void_p
        lib.ms_create.argtypes = [ctypes.c_double, ctypes.c_int]
        lib.ms_destroy.argtypes = [ctypes.c_void_p]
        lib.ms_add_task.restype = ctypes.c_int64
        lib.ms_add_task.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64]
        lib.ms_get_task.restype = ctypes.c_int
        lib.ms_get_task.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64,
                                    ctypes.POINTER(ctypes.c_int64)]
        lib.ms_task_finished.restype = ctypes.c_int
        lib.ms_task_finished.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ms_task_failed.restype = ctypes.c_int
        lib.ms_task_failed.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.ms_new_pass.argtypes = [ctypes.c_void_p]
        lib.ms_counts.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int64)]
        lib.ms_snapshot.restype = ctypes.c_int64
        lib.ms_snapshot.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64]
        lib.ms_restore.restype = ctypes.c_int
        lib.ms_restore.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64]
        _lib = lib
        return _lib


def lib_available():
    return _load() is not None


class RecordIOWriter(object):
    """(reference recordio/writer.h)"""

    def __init__(self, path, compressor='zlib', max_chunk_bytes=1 << 20):
        lib = _load()
        self._lib = lib
        self._py_records = None
        self._path = path
        if lib is None:
            self._py_records = []
            self._compressor = compressor
            return
        self._h = lib.recordio_writer_create(
            path.encode(), 1 if compressor == 'zlib' else 0,
            max_chunk_bytes)
        if not self._h:
            raise IOError('cannot open %s for writing' % path)

    def write(self, data):
        if isinstance(data, str):
            data = data.encode()
        if self._py_records is not None:
            self._py_records.append(bytes(data))
            return
        if self._lib.recordio_writer_write(self._h, data, len(data)) != 0:
            raise IOError('recordio write failed')

    def close(self):
        if self._py_records is not None:
            _py_write_recordio(self._path, self._py_records,
                               self._compressor)
            return
        if self._lib.recordio_writer_close(self._h) != 0:
            raise IOError('recordio close/flush failed')
        self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class RecordIOScanner(object):
    """(reference recordio/scanner.h)"""

    def __init__(self, path):
        lib = _load()
        self._lib = lib
        if lib is None:
            self._records = iter(_py_read_recordio(path))
            self._h = None
            return
        self._h = lib.recordio_scanner_create(path.encode())
        if not self._h:
            raise IOError('cannot open %s' % path)

    def __iter__(self):
        return self

    def __next__(self):
        if self._h is None:
            return next(self._records)
        buf = ctypes.c_char_p()
        length = ctypes.c_uint64()
        status = self._lib.recordio_scanner_next(self._h, ctypes.byref(buf),
                                                 ctypes.byref(length))
        if status == 0:
            raise StopIteration
        if status < 0:
            raise IOError('corrupt recordio chunk (crc/format)')
        return ctypes.string_at(buf, length.value)

    def close(self):
        if self._h is not None:
            self._lib.recordio_scanner_destroy(self._h)
            self._h = None


# --- pure-python fallback implementing the same on-disk format ---
def _py_write_recordio(path, records, compressor='zlib'):
    import struct
    import zlib as _z
    with open(path, 'wb') as f:
        raw = b''.join(
            struct.pack('<I', len(r)) + r for r in records)
        stored = _z.compress(raw, 1) if compressor == 'zlib' else raw
        comp = 1 if compressor == 'zlib' else 0
        f.write(
            struct.pack('<6I', 0x0c010cec, comp, len(records), len(raw),
                        len(stored), _z.crc32(stored) & 0xffffffff))
        f.write(stored)


def _py_read_recordio(path):
    import struct
    import zlib as _z
    out = []
    with open(path, 'rb') as f:
        while True:
            hdr = f.read(24)
            if len(hdr) < 24:
                break
            magic, comp, n, raw_len, stored_len, crc = struct.unpack(
                '<6I', hdr)
            if magic != 0x0c010cec:
                raise IOError('bad recordio magic')
            stored = f.read(stored_len)
            if _z.crc32(stored) & 0xffffffff != crc:
                raise IOError('recordio crc mismatch')
            raw = _z.decompress(stored) if comp else stored
            off = 0
            for _ in range(n):
                (l, ) = struct.unpack_from('<I', raw, off)
                off += 4
                out.append(raw[off:off + l])
                off += l
    return out


class NativeBlockingQueue(object):
    """Bounded producer/consumer byte queue
    (reference operators/reader/lod_tensor_blocking_queue.h)."""

    def __init__(self, capacity):
        lib = _load()
        self._lib = lib
        if lib is None:
            import queue as _q
            self._q = _q.Queue(maxsize=capacity)
            self._closed = False
            return
        self._q = None
        self._h = lib.bq_create(capacity)
        self._pop_cap = 1 << 16  # size hint only; buffers are per-call

    def push(self, data):
        if self._q is not None:
            import queue as _q
            # bounded wait so close() interrupts a blocked producer like
            # the native bq_push does
            while not self._closed:
                try:
                    self._q.put(bytes(data), timeout=0.05)
                    return True
                except _q.Full:
                    continue
            return False
        return self._lib.bq_push(self._h, bytes(data), len(data)) == 0

    def pop(self):
        """bytes, or None when closed + drained."""
        if self._q is not None:
            import queue as _q
            while True:
                try:
                    return self._q.get(timeout=0.05)
                except _q.Empty:
                    if self._closed:
                        return None
        cap = self._pop_cap
        while True:
            # per-call buffer: concurrent consumers never share bytes
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.bq_pop(self._h, buf, cap)
            if n == -1:
                return None
            if n <= -2:  # buffer too small: grow and retry
                cap = -(n + 2)
                self._pop_cap = max(self._pop_cap, cap)
                continue
            return buf.raw[:n]

    def size(self):
        if self._q is not None:
            return self._q.qsize()
        return self._lib.bq_size(self._h)

    def close(self):
        if self._q is not None:
            self._closed = True
            return
        self._lib.bq_close(self._h)

    def reopen(self):
        if self._q is not None:
            import queue as _q
            self._q = _q.Queue(maxsize=self._q.maxsize)
            self._closed = False
            return
        self._lib.bq_reopen(self._h)

    def __del__(self):
        try:
            if self._q is None and self._lib is not None:
                self._lib.bq_destroy(self._h)
        except Exception:
            pass


def host_pool_stats():
    lib = _load()
    if lib is None:
        return {'in_use': 0, 'cached': 0, 'peak': 0, 'native': False}
    return {
        'in_use': int(lib.hp_in_use()),
        'cached': int(lib.hp_cached()),
        'peak': int(lib.hp_peak()),
        'native': True,
    }


class _PyChan(object):
    """Pure-Python mirror of csrc/channel.cc — same rendezvous, try and
    close-drain semantics, used when the native lib is unavailable."""

    def __init__(self, capacity):
        self.capacity = capacity
        self._cond = threading.Condition()
        self._items = []
        self._recv_waiters = 0
        self._sent_seq = 0
        self._taken_seq = 0
        self._closed = False

    def send(self, data):
        with self._cond:
            eff = self.capacity or 1
            self._cond.wait_for(
                lambda: self._closed or len(self._items) < eff)
            if self._closed:
                return False
            self._items.append(bytes(data))
            self._sent_seq += 1
            my_seq = self._sent_seq
            self._cond.notify_all()
            if self.capacity == 0:
                self._cond.wait_for(
                    lambda: self._closed or self._taken_seq >= my_seq)
                if self._taken_seq < my_seq:
                    # closed before pickup: withdraw the payload so a
                    # close-drain recv can't deliver a message already
                    # reported as failed (mirrors csrc/channel.cc)
                    if self._items and self._sent_seq == my_seq:
                        self._items.pop()
                        self._sent_seq -= 1
                    return False
            return True

    def try_send(self, data):
        with self._cond:
            if self._closed:
                return NativeChannel.CLOSED
            if self.capacity == 0:
                if self._recv_waiters <= 0 or self._items:
                    return NativeChannel.WOULD_BLOCK
            elif len(self._items) >= self.capacity:
                return NativeChannel.WOULD_BLOCK
            self._items.append(bytes(data))
            self._sent_seq += 1
            self._cond.notify_all()
            return True

    def _pop_locked(self):
        item = self._items.pop(0)
        self._taken_seq += 1
        self._cond.notify_all()
        return item

    def recv(self):
        with self._cond:
            self._recv_waiters += 1
            self._cond.notify_all()
            self._cond.wait_for(lambda: self._closed or self._items)
            self._recv_waiters -= 1
            if not self._items:
                return NativeChannel.CLOSED
            return self._pop_locked()

    def try_recv(self):
        with self._cond:
            if not self._items:
                return (NativeChannel.CLOSED
                        if self._closed else NativeChannel.WOULD_BLOCK)
            return self._pop_locked()

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def size(self):
        with self._cond:
            return len(self._items)


class NativeChannel(object):
    """CSP channel over the native runtime (csrc/channel.cc), with a pure
    Python fallback (_PyChan) implementing the same semantics.
    capacity=0 means unbuffered rendezvous (reference framework/channel.h
    MakeChannel semantics)."""

    WOULD_BLOCK = object()
    CLOSED = object()

    def __init__(self, capacity=0):
        self.capacity = capacity
        lib = _load()
        self._lib = lib
        if lib is None:
            self._q = _PyChan(capacity)
            self._cap = 1 << 12
            return
        self._q = None
        self._h = lib.ch_create(capacity)
        self._cap = 1 << 12

    # payloads are opaque bytes; serialization lives in fluid.concurrency
    def send(self, data):
        """True on delivery, False if the channel is/was closed."""
        if self._q is not None:
            return self._q.send(data)
        return self._lib.ch_send(self._h, bytes(data), len(data)) == 0

    def try_send(self, data):
        if self._q is not None:
            return self._q.try_send(data)
        r = self._lib.ch_try_send(self._h, bytes(data), len(data))
        if r == 0:
            return True
        return self.CLOSED if r == -1 else self.WOULD_BLOCK

    def _recv_native(self, fn):
        cap = self._cap
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = fn(self._h, buf, cap)
            if n == -1:
                return self.CLOSED
            if n == -2:
                return self.WOULD_BLOCK
            if n <= -3:
                cap = -(n + 3)
                self._cap = max(self._cap, cap)
                continue
            return buf.raw[:n]

    def recv(self):
        """bytes, or CLOSED after close+drain."""
        if self._q is not None:
            return self._q.recv()
        return self._recv_native(self._lib.ch_recv)

    def try_recv(self):
        if self._q is not None:
            return self._q.try_recv()
        return self._recv_native(self._lib.ch_try_recv)

    def close(self):
        if self._q is not None:
            self._q.close()
            return
        self._lib.ch_close(self._h)

    def size(self):
        if self._q is not None:
            return self._q.size()
        return int(self._lib.ch_size(self._h))

    def __del__(self):
        try:
            if self._q is None and self._lib is not None:
                self._lib.ch_destroy(self._h)
        except Exception:
            pass
