"""Cross-model HBM arbitration: budgeted admission + LRU weight
eviction for the multi-model registry.

A TPU chip's HBM is one pool shared by every loaded model's weights and
compiled executables; the reference Fluid stack never arbitrated it —
one predictor per process, OOM as the admission policy.  Real
multi-model servers (TF-Serving's model manager, Pathways-style
multi-tenant sharing) treat the fleet's footprint as a first-class
resource.  The ``HBMArbiter`` is that subsystem's ledger:

  * each model carries an ACCOUNT — bytes charged against the budget —
    SEEDED from ``fluid.contrib.memory_usage_calc.memory_usage`` (the
    program's var-sum upper bound at the top bucket size, covering
    weights + per-dispatch activations the executables pin) and
    CORRECTED to live jax buffer statistics once the model has served
    (the engine's ``device_footprint()`` — the ground truth XLA
    actually allocated for the weights);
  * ``admit`` is the load-time gate: a model whose seed alone exceeds
    the budget raises ``HBMBudgetError`` (typed — callers distinguish
    capacity from bugs) instead of letting XLA OOM mid-request;
  * ``ensure`` is the dispatch-time gate: before a model serves, the
    least-recently-USED resident models are evicted (weights demoted to
    host memory via the registry's evict callback) until the target
    fits — reload is transparent on its next request;
  * every decision is counted (``evictions``, ``reloads``,
    ``admission_rejects``) and snapshotted for ``registry.metrics()``.

The arbiter is pure accounting + policy: it never touches device
memory itself.  The registry supplies the evict callback, which runs
under the victim engine's ``paused()`` window.
"""

import collections
import threading
import time

__all__ = ['HBMArbiter', 'HBMBudgetError', 'program_seed_bytes']

_UNIT_BYTES = {'B': 1, 'KB': 1024, 'MB': 1024**2, 'GB': 1024**3}


def program_seed_bytes(program, batch_size):
    """The admission seed: memory_usage's UPPER estimate for one
    forward pass at ``batch_size``, in bytes.  Deliberately the high
    bound — admission must be conservative; the live correction pulls
    the account down to what XLA really allocated."""
    from ..fluid.contrib.memory_usage_calc import memory_usage
    _, high, unit = memory_usage(program, batch_size)
    return int(high * _UNIT_BYTES[unit])


class HBMBudgetError(RuntimeError):
    """Typed admission rejection: the model cannot fit the registry's
    HBM budget even with every other model evicted.  Carries the
    offending account so callers can size budgets programmatically."""

    def __init__(self, name, need_bytes, budget_bytes):
        self.model = name
        self.need_bytes = int(need_bytes)
        self.budget_bytes = int(budget_bytes)
        super(HBMBudgetError, self).__init__(
            'model %r needs ~%d bytes of HBM but the registry budget is '
            '%d bytes — raise hbm_budget_bytes or shrink the model/'
            'bucket ladder' % (name, need_bytes, budget_bytes))


class _Account(object):
    __slots__ = ('bytes', 'resident', 'source')

    def __init__(self, nbytes, resident, source):
        self.bytes = int(nbytes)
        self.resident = resident
        self.source = source  # 'seed' | 'live'


class HBMArbiter(object):
    """Budgeted accounts over the registry's models, LRU-ordered by
    last use.  ``budget_bytes=None`` disables enforcement (accounting
    and counters still run — the observability is free)."""

    def __init__(self, budget_bytes=None):
        self.budget_bytes = (int(budget_bytes)
                             if budget_bytes is not None else None)
        # insertion order IS the LRU order: touch() re-appends
        self._accounts = collections.OrderedDict()
        self._lock = threading.RLock()
        self.evictions = 0
        self.reloads = 0
        self.admission_rejects = 0
        self.last_audit = None

    def set_budget(self, budget_bytes):
        """Re-point the budget (tightening it does NOT evict eagerly —
        the next ensure() call enforces the new bound)."""
        with self._lock:
            self.budget_bytes = (int(budget_bytes)
                                 if budget_bytes is not None else None)

    def resident_bytes(self, exclude=None):
        with self._lock:
            return sum(a.bytes for n, a in self._accounts.items()
                       if a.resident and n != exclude)

    def admit(self, name, seed_bytes, ensure_cb=None):
        """Open an account at load time.  Raises HBMBudgetError when the
        seed alone can never fit; otherwise registers the account
        non-resident and lets ``ensure`` (via ensure_cb, usually
        registry-internal) make room."""
        seed_bytes = int(seed_bytes)
        with self._lock:
            if self.budget_bytes is not None and \
                    seed_bytes > self.budget_bytes:
                self.admission_rejects += 1
                raise HBMBudgetError(name, seed_bytes, self.budget_bytes)
            self._accounts[name] = _Account(seed_bytes, False, 'seed')
        if ensure_cb is not None:
            ensure_cb(name)

    def ensure(self, name, evict_cb):
        """Make ``name`` resident within budget: evict least-recently-
        used OTHER resident models (evict_cb(victim) must demote the
        victim's weights and return its live byte count) until the
        account fits.  Returns True when this call transitioned the
        model to resident (a reload when it had been evicted before).
        Counts as LRU use."""
        with self._lock:
            acct = self._accounts[name]
            self._accounts.move_to_end(name)
            was_resident = acct.resident
            if self.budget_bytes is not None:
                # evict in LRU order until the target fits
                while acct.bytes + self.resident_bytes(exclude=name) \
                        > self.budget_bytes:
                    victim = next(
                        (n for n, a in self._accounts.items()
                         if a.resident and n != name), None)
                    if victim is None:
                        self.admission_rejects += 1
                        raise HBMBudgetError(
                            name, acct.bytes, self.budget_bytes)
                    self.evict(victim, evict_cb)
            acct.resident = True
            if not was_resident and acct.source == 'live':
                # it served before and was evicted: this is a reload
                self.reloads += 1
            return not was_resident

    def evict(self, name, evict_cb):
        """Demote one model (the callback moves the buffers) and mark
        its account non-resident, corrected to the live bytes that
        actually moved."""
        with self._lock:
            acct = self._accounts[name]
            if not acct.resident:
                return 0
            moved = evict_cb(name)
            if moved:
                acct.bytes = int(moved)
                acct.source = 'live'
            acct.resident = False
            self.evictions += 1
            return moved

    def correct(self, name, live_bytes):
        """Live-stat correction: once a model has real device buffers,
        its account tracks them instead of the seed estimate (the
        'corrected by live jax buffer stats' half of the contract)."""
        live_bytes = int(live_bytes)
        if live_bytes <= 0:
            return
        with self._lock:
            acct = self._accounts.get(name)
            if acct is not None and acct.resident:
                acct.bytes = live_bytes
                acct.source = 'live'

    def touch(self, name):
        with self._lock:
            if name in self._accounts:
                self._accounts.move_to_end(name)

    def drop(self, name):
        with self._lock:
            self._accounts.pop(name, None)

    def is_resident(self, name):
        with self._lock:
            acct = self._accounts.get(name)
            return bool(acct is not None and acct.resident)

    def audit(self, live_bytes=None):
        """Cross-check the ledger against the runtime's OWN buffer
        stats (the ROADMAP's carried-over ``jax.live_arrays()`` item):
        ``live_bytes`` defaults to the byte sum of every live
        device-resident jax.Array in the process.  The drift —
        live minus accounted-resident — is the metric: a ledger
        matching reality sits near the transient feed/fetch buffer
        noise; a leak (an evicted model whose buffers never moved, an
        account stuck on a stale seed) walks away from zero.  The
        result is kept as ``last_audit`` and rides ``snapshot()`` /
        ``registry.metrics()``."""
        if live_bytes is None:
            import jax
            live_bytes = 0
            for arr in jax.live_arrays():
                try:
                    if arr.is_deleted():
                        continue
                    live_bytes += int(arr.nbytes)
                except Exception:
                    continue  # a donated/invalidated array mid-walk
        with self._lock:
            accounted = self.resident_bytes()
            audit = {
                'live_bytes': int(live_bytes),
                'accounted_bytes': int(accounted),
                'drift_bytes': int(live_bytes) - int(accounted),
                'ts': time.time(),
            }
            self.last_audit = audit
        return dict(audit)

    def snapshot(self):
        with self._lock:
            return {
                'budget_bytes': self.budget_bytes,
                'resident_bytes': self.resident_bytes(),
                'evictions': self.evictions,
                'reloads': self.reloads,
                'admission_rejects': self.admission_rejects,
                'audit': (dict(self.last_audit)
                          if self.last_audit else None),
                'accounts': {
                    n: {'bytes': a.bytes, 'resident': a.resident,
                        'source': a.source}
                    for n, a in self._accounts.items()
                },
                'lru_order': list(self._accounts),
            }
