"""Shape bucketing: map variable request batch sizes onto a small fixed
set of compiled entries.

Every distinct feed signature is one XLA compile (the static-shape
design's recompile cost — Executor keys its cache on the scanned-shape
signature, executor.py:_resolve_and_compile / note_eval_compile), so a
serving workload whose request sizes wander over 1..max_batch must not
mint O(max_batch) executables.  The batch-dim answer mirrors
executor._bucketed_len's sequence-length ladder, but batch sizes are
small and latency-bound, so the default ladder is simply the powers of
two up to ``max_batch_size`` (aligned up to ``multiple`` — the dp mesh
extent for sharded serving): padding waste < 50%, log2(max_batch)
batch shapes.  (The engine's lots-per-dispatch count is quantized to
its own power-of-two ladder — engine._collect_block — so the total
executable set is bounded at buckets x (log2(steps_per_dispatch)+1),
not buckets x K.)

The set is BOUNDED: at most ``max_buckets`` buckets stay active, LRU
evicted beyond that.  Eviction here is accounting — the Executor's own
LRU (64 entries) owns executable memory — but the report makes the
compile budget observable: the engine surfaces ``report()`` plus the
executor's ``compile_count`` through its metrics snapshot.
"""

import collections
import threading

__all__ = ['ShapeBucketSet']


def _align_up(n, multiple):
    return -(-int(n) // multiple) * multiple if multiple > 1 else int(n)


class ShapeBucketSet(object):
    """The bounded ladder of padded batch sizes serving requests map to.

    sizes: explicit ladder (sorted, deduped, aligned to ``multiple``);
    None builds the default powers-of-two ladder up to max_batch_size.
    """

    def __init__(self, max_batch_size, sizes=None, multiple=1,
                 max_buckets=16):
        self.max_batch_size = int(max_batch_size)
        self.multiple = max(int(multiple), 1)
        if sizes is None:
            sizes, s = [], 1
            while True:
                aligned = _align_up(s, self.multiple)
                if aligned >= self.max_batch_size:
                    sizes.append(_align_up(self.max_batch_size,
                                           self.multiple))
                    break
                sizes.append(aligned)
                s *= 2
        else:
            sizes = [_align_up(s, self.multiple) for s in sizes]
            top = _align_up(self.max_batch_size, self.multiple)
            if max(sizes) < top:
                # the batcher coalesces up to max_batch_size rows no
                # matter the ladder — a short explicit ladder would send
                # every above-top lot to its own exact bucket, quietly
                # voiding the bounded-compile contract
                sizes.append(top)
        self.sizes = sorted(set(int(s) for s in sizes))
        self._max_buckets = int(max_buckets)
        self._active = collections.OrderedDict()  # bucket -> hit count
        # bucket_for runs on the engine's worker thread while report()
        # serves metrics()/the profiler sidecar from user threads — the
        # OrderedDict must not be iterated mid-mutation
        self._lock = threading.Lock()
        self.evictions = 0
        self.oversized = 0

    def bucket_for(self, rows):
        """Padded batch size for a lot of ``rows`` real rows: the
        smallest ladder entry that fits.  A lone request larger than the
        ladder top gets its own exact (multiple-aligned) bucket rather
        than being rejected — it still compiles once per distinct size,
        which the ``oversized`` counter makes visible."""
        rows = int(rows)
        if rows < 1:
            raise ValueError('bucket_for: rows must be >= 1, got %r'
                             % (rows, ))
        for s in self.sizes:
            if rows <= s:
                bucket = s
                break
        else:
            bucket = _align_up(rows, self.multiple)
        with self._lock:
            if bucket > self.sizes[-1]:
                self.oversized += 1
            if bucket in self._active:
                self._active[bucket] += 1
                self._active.move_to_end(bucket)
            else:
                self._active[bucket] = 1
                if len(self._active) > self._max_buckets:
                    self._active.popitem(last=False)
                    self.evictions += 1
        return bucket

    def report(self):
        """Observability snapshot: the ladder, the active (bounded) set
        with hit counts, and the eviction/oversize tallies."""
        with self._lock:
            return {
                'sizes': list(self.sizes),
                'active': list(self._active),
                'hits': dict(self._active),
                'evictions': self.evictions,
                'oversized': self.oversized,
                'max_buckets': self._max_buckets,
            }
