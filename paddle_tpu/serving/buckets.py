"""Shape bucketing: map variable request shapes onto a small fixed set
of compiled entries.

Every distinct feed signature is one XLA compile (the static-shape
design's recompile cost — Executor keys its cache on the scanned-shape
signature, executor.py:_resolve_and_compile / note_eval_compile), so a
serving workload whose request sizes wander over 1..max_batch must not
mint O(max_batch) executables.  The batch-dim answer mirrors the
seq-len ladder (fluid.shape_policy), but batch sizes are small and
latency-bound, so the default ladder is simply the powers of two up to
``max_batch_size`` (aligned up to ``multiple`` — the dp mesh extent
for sharded serving): padding waste < 50%, log2(max_batch) batch
shapes.  (The engine's lots-per-dispatch count is quantized to its own
power-of-two ladder — engine._collect_block — so the total executable
set is bounded at buckets x (log2(steps_per_dispatch)+1), not
buckets x K.)

``TrailingDimBuckets`` is the TRAILING-dim twin (ISSUE 5): per-feed
seq-len/resolution ladders seeded from the SAME
``fluid.shape_policy.bucketed_len`` policy the executor applies to LoD
max-lens, so requests with distinct trailing shapes (seq-len,
resolution) quantize to shared rungs and coalesce instead of minting
per-shape lots and per-shape executables.

Both sets are BOUNDED: at most ``max_buckets`` buckets stay active, LRU
evicted beyond that.  Eviction here is accounting — the Executor's own
LRU (64 entries) owns executable memory — but the report makes the
compile budget observable: the engine surfaces ``report()`` plus the
executor's ``compile_count`` through its metrics snapshot.

Lock discipline (audited, ISSUE 5 satellite): ``bucket_for`` runs on
the engine's worker/submitter threads while ``report()`` serves
metrics()/the profiler sidecar from user threads.  The ladder
(``sizes`` / ``_ladders``) is immutable after __init__; EVERY mutable
member (the active-set OrderedDict, the eviction/oversize tallies) is
read and written only under ``_lock``, so a report snapshot can never
observe an LRU eviction mid-update (tests/test_trailing_buckets.py
hammers this invariant from concurrent threads).
"""

import collections
import threading

from ..fluid import shape_policy

__all__ = ['ShapeBucketSet', 'TrailingDimBuckets']


def _align_up(n, multiple):
    return -(-int(n) // multiple) * multiple if multiple > 1 else int(n)


class ShapeBucketSet(object):
    """The bounded ladder of padded batch sizes serving requests map to.

    sizes: explicit ladder (sorted, deduped, aligned to ``multiple``);
    None builds the default powers-of-two ladder up to max_batch_size.
    """

    def __init__(self, max_batch_size, sizes=None, multiple=1,
                 max_buckets=16):
        self.max_batch_size = int(max_batch_size)
        self.multiple = max(int(multiple), 1)
        if sizes is None:
            sizes, s = [], 1
            while True:
                aligned = _align_up(s, self.multiple)
                if aligned >= self.max_batch_size:
                    sizes.append(_align_up(self.max_batch_size,
                                           self.multiple))
                    break
                sizes.append(aligned)
                s *= 2
        else:
            sizes = [_align_up(s, self.multiple) for s in sizes]
            top = _align_up(self.max_batch_size, self.multiple)
            if max(sizes) < top:
                # the batcher coalesces up to max_batch_size rows no
                # matter the ladder — a short explicit ladder would send
                # every above-top lot to its own exact bucket, quietly
                # voiding the bounded-compile contract
                sizes.append(top)
        self.sizes = sorted(set(int(s) for s in sizes))
        if int(max_buckets) < 1:
            raise ValueError('max_buckets must be >= 1')
        self._max_buckets = int(max_buckets)
        self._active = collections.OrderedDict()  # bucket -> hit count
        # bucket_for runs on the engine's worker thread while report()
        # serves metrics()/the profiler sidecar from user threads — the
        # OrderedDict must not be iterated mid-mutation
        self._lock = threading.Lock()
        self.evictions = 0
        self.oversized = 0

    def bucket_for(self, rows):
        """Padded batch size for a lot of ``rows`` real rows: the
        smallest ladder entry that fits.  A lone request larger than the
        ladder top gets its own exact (multiple-aligned) bucket rather
        than being rejected — it still compiles once per distinct size,
        which the ``oversized`` counter makes visible."""
        rows = int(rows)
        if rows < 1:
            raise ValueError('bucket_for: rows must be >= 1, got %r'
                             % (rows, ))
        for s in self.sizes:
            if rows <= s:
                bucket = s
                break
        else:
            bucket = _align_up(rows, self.multiple)
        with self._lock:
            if bucket > self.sizes[-1]:
                self.oversized += 1
            if bucket in self._active:
                self._active[bucket] += 1
                self._active.move_to_end(bucket)
            else:
                self._active[bucket] = 1
                if len(self._active) > self._max_buckets:
                    self._active.popitem(last=False)
                    self.evictions += 1
        return bucket

    def report(self):
        """Observability snapshot: the ladder, the active (bounded) set
        with hit counts, and the eviction/oversize tallies.  Runs
        entirely under ``_lock`` (see the module docstring's lock
        audit): the OrderedDict copy, the eviction and the oversize
        counters all come from ONE consistent point in time."""
        with self._lock:
            return {
                'sizes': list(self.sizes),
                'active': list(self._active),
                'hits': dict(self._active),
                'evictions': self.evictions,
                'oversized': self.oversized,
                'max_buckets': self._max_buckets,
            }


class TrailingDimBuckets(object):
    """Bounded per-feed TRAILING-dim ladders (the seq-len/resolution
    twin of ShapeBucketSet, ISSUE 5).

    ``bucket_for(name, axis, extent)`` returns the padded extent a
    request's trailing dim quantizes to:

      * by default, the shared seq-len policy
        ``fluid.shape_policy.bucketed_len`` — the SAME ladder the
        executor applies to LoD max-lens, so the request path and the
        feed-lowering path stop being parallel inventions;
      * feeds named in ``ladders`` use their EXPLICIT rung list instead
        (a resolution ladder: ``{'img': [224, 256, 320]}`` applies to
        axis 1; ``{'img': {2: [224, 256], 3: [224, 256]}}`` names the
        axes).  An extent above the explicit top gets its own exact
        rung (counted ``oversized``) rather than being rejected.

    The active set is bounded at ``max_buckets`` (name, axis, rung)
    entries, LRU-evicted beyond that — accounting, like
    ShapeBucketSet's: the Executor's compile LRU owns executable
    memory; this report makes the per-dim compile budget observable.

    Lock discipline matches ShapeBucketSet (module docstring): the
    ladder table is immutable after __init__, every mutable member
    lives under ``_lock``.
    """

    def __init__(self, ladders=None, bucket=None, max_buckets=32):
        self.bucket = int(bucket) if bucket else shape_policy.SEQ_BUCKET
        lad = {}
        for name, spec in (ladders or {}).items():
            if isinstance(spec, dict):
                for axis, sizes in spec.items():
                    lad[(name, int(axis))] = sorted(
                        set(int(s) for s in sizes))
            else:
                lad[(name, 1)] = sorted(set(int(s) for s in spec))
        for key, sizes in lad.items():
            if key[1] < 1:
                # axis 0 is the BATCH dim (ShapeBucketSet's job); a
                # <1 axis would be silently skipped downstream
                raise ValueError(
                    'TrailingDimBuckets: ladder axis for %r must be '
                    '>= 1 (axis 0 is the batch dim — that ladder is '
                    'ShapeBucketSet/bucket_sizes)' % (key[0], ))
            if not sizes or min(sizes) < 1:
                raise ValueError(
                    'TrailingDimBuckets: ladder for %r must be a non-'
                    'empty list of positive extents, got %r'
                    % (key, sizes))
        self._ladders = lad
        if int(max_buckets) < 1:
            raise ValueError(
                'TrailingDimBuckets: max_buckets must be >= 1')
        self._max_buckets = int(max_buckets)
        self._active = collections.OrderedDict()  # (name,axis,rung)->hits
        self._lock = threading.Lock()
        self.evictions = 0
        self.oversized = 0

    def ladder_axes(self, name):
        """The axes an EXPLICIT ladder was configured for (dense feeds
        opt into trailing bucketing per feed; seq feeds with @SEQLEN
        lengths ride the default policy on axis 1)."""
        return sorted(axis for (n, axis) in self._ladders if n == name)

    def bucket_for(self, name, axis, extent):
        """Padded extent for feed ``name``'s trailing ``axis`` of real
        ``extent``: the explicit ladder's smallest covering rung, or
        the shared seq-len policy when no ladder names the feed."""
        extent = int(extent)
        if extent < 1:
            raise ValueError(
                'bucket_for: extent must be >= 1, got %r' % (extent, ))
        sizes = self._ladders.get((name, int(axis)))
        oversize = False
        if sizes is None:
            rung = shape_policy.bucketed_len(extent, self.bucket)
        else:
            for s in sizes:
                if extent <= s:
                    rung = s
                    break
            else:
                rung = extent  # above the explicit top: own exact rung
                oversize = True
        key = (name, int(axis), rung)
        with self._lock:
            if oversize:
                self.oversized += 1
            if key in self._active:
                self._active[key] += 1
                self._active.move_to_end(key)
            else:
                self._active[key] = 1
                if len(self._active) > self._max_buckets:
                    self._active.popitem(last=False)
                    self.evictions += 1
        return rung

    def report(self):
        """Observability snapshot (one consistent point in time, under
        ``_lock``): per-(feed, axis, rung) hit counts plus the
        eviction/oversize tallies.  Keys are rendered ``name[axis]:rung``
        so the snapshot is JSON-friendly in the profiler sidecar."""
        with self._lock:
            hits = {'%s[%d]:%d' % k: v for k, v in self._active.items()}
            return {
                'policy_bucket': self.bucket,
                'ladders': {'%s[%d]' % k: list(v)
                            for k, v in self._ladders.items()},
                'active': list(hits),
                'hits': hits,
                'evictions': self.evictions,
                'oversized': self.oversized,
                'max_buckets': self._max_buckets,
            }
