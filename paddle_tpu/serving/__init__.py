"""paddle_tpu.serving — the TPU-native inference serving engine.

Takes a loaded inference program (fluid.io.load_inference_model) and
serves it request-facing: dynamic micro-batching (MicroBatcher),
shape-bucketed compiles (ShapeBucketSet), pipelined multi-step eval
dispatch (Executor.run_eval_multi / ParallelExecutor.run_eval_multi for
dp>1 sharded serving), and engine metrics surfaced through
fluid.profiler's timeline.  See engine.py for the design and the README
'Serving engine' section for the knobs.

    engine = serving.InferenceEngine.from_saved_model('/path/to/model')
    with engine:                         # starts the worker thread
        fut = engine.submit({'img': x})  # coalesces with other callers
        logits, = fut.result()
    print(engine.metrics())
"""

from .batcher import InferenceRequest, MicroBatcher  # noqa: F401
from .buckets import ShapeBucketSet  # noqa: F401
from .engine import InferenceEngine, ServingConfig  # noqa: F401
from .metrics import EngineMetrics  # noqa: F401

__all__ = ['InferenceEngine', 'ServingConfig', 'MicroBatcher',
           'InferenceRequest', 'ShapeBucketSet', 'EngineMetrics']
