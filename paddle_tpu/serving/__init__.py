"""paddle_tpu.serving — the TPU-native inference serving stack.

Single-model: ``InferenceEngine`` serves a loaded inference program
(fluid.io.load_inference_model) request-facing — dynamic micro-batching
(MicroBatcher), shape-bucketed compiles (ShapeBucketSet), pipelined
multi-step eval dispatch (Executor.run_eval_multi /
ParallelExecutor.run_eval_multi for dp>1 sharded serving), and engine
metrics surfaced through fluid.profiler's timeline.

Generation: an engine built with ``generation=GenerationSpec(...)``
gains ``submit_generate`` — a continuous-batching autoregressive decode
lane: prompts prefill through the normal micro-batch/bucketing
machinery, per-request decoder state (KV/hidden) lives in a slot-based
``SlotStateCache`` resident in HBM, and an in-jit decode scan
(Executor.run_decode_multi / ParallelExecutor.run_decode_multi) runs K
greedy steps per dispatch over the whole slot batch with per-request
stop conditions masked inside — token-identical to per-request decode
at a fraction of the dispatches.

Multi-model: ``ModelRegistry`` hosts N named engines over one shared
device/mesh with cross-model HBM arbitration (``HBMArbiter``) —
budgeted admission, LRU weight eviction to host memory with transparent
reload, a fair request router, and per-model ``:serving/<model>``
timeline rows.  See engine.py / registry.py for the designs and the
README 'Serving engine' / 'Multi-model serving' sections for the knobs.

Pipelined decode (ISSUE 9): the decode lane keeps up to
``decode_pipeline_depth`` chained scans in flight — scan N+1 is
enqueued against scan N's device-resident (donated) output carry while
the host harvests N's token block asynchronously, so device
utilization no longer pays a host round trip per scan; shedding and
admission use per-signature ``ServiceTimeProfile`` estimates and the
registry's overload watermarks can track drain-vs-arrival rates
(``ServingConfig(adaptive_admission=True)``).

SLOs (ISSUE 8): requests carry ``priority`` and ``deadline_ms`` —
lot formation is deadline-aware (EDF within priority classes) and
past-deadline work is SHED with a typed ``DeadlineExceededError``
instead of served late; the registry refuses requests at the door with
``OverloadedError`` (+ retry-after hint) once a model's queue crosses
its depth/age watermarks; ``registry.warm()`` records a replayable
compile catalog next to FLAGS_xla_compile_cache_dir and
``registry.prewarm()`` replays it so a restarted fleet compiles
nothing on first traffic; and ``OpenLoopLoadGen`` (loadgen.py) drives
the whole stack with seeded Poisson arrivals, reporting sustained
req/s, p50/p99/p99.9 and goodput.  README 'Serving SLOs' has the
operator's view; tools/load_gen.py is the CLI.

Fleet tier (ISSUE 17): ``ReplicaServer`` serves one registry over the
shared RPC substrate (distributed/transport.py — typed errors, seeded
retries, exactly-once dedup) and ``FleetRouter`` fronts N replicas
with load-balanced dispatch, decode-session affinity (``session=``
pins a generation's decode state to one replica), fleet-level typed
overload, and replica-death failover that re-prefills in-flight
generations on a survivor — token-identical under greedy decode.  See
fleet.py and the README 'Serving fleet' section.

    reg = serving.ModelRegistry(hbm_budget_bytes=2 << 30)
    reg.load('ranker', '/models/ranker')
    with reg:                                  # starts every worker
        fut = reg.submit('ranker', {'img': x})
        logits, = fut.result()
    print(reg.status())
"""

from .arbiter import HBMArbiter, HBMBudgetError  # noqa: F401
from .batcher import InferenceRequest, MicroBatcher  # noqa: F401
from .buckets import ShapeBucketSet, TrailingDimBuckets  # noqa: F401
from .decode import GenerationRequest, GenerationSpec, \
    SlotStateCache  # noqa: F401
from .engine import InferenceEngine, ServingConfig  # noqa: F401
from .errors import DeadlineExceededError, EngineClosedError, \
    OverloadedError  # noqa: F401
from .fleet import FleetFuture, FleetRouter, ReplicaServer  # noqa: F401
from .loadgen import OpenLoopLoadGen, TrafficClass  # noqa: F401
from .metrics import EngineMetrics  # noqa: F401
from .profile import ServiceTimeProfile  # noqa: F401
from .registry import ModelRegistry  # noqa: F401

__all__ = ['InferenceEngine', 'ServingConfig', 'MicroBatcher',
           'InferenceRequest', 'ShapeBucketSet', 'TrailingDimBuckets',
           'EngineMetrics', 'ModelRegistry', 'HBMArbiter',
           'HBMBudgetError', 'GenerationSpec', 'GenerationRequest',
           'SlotStateCache', 'DeadlineExceededError', 'OverloadedError',
           'EngineClosedError', 'OpenLoopLoadGen', 'TrafficClass',
           'ServiceTimeProfile', 'ReplicaServer', 'FleetRouter',
           'FleetFuture']
