"""Serving fleet tier (ISSUE 17): N replica registries behind a
resilient, affinity-aware router.

One ``ModelRegistry`` saturates one device mesh; a serving FLEET runs N
replica registry processes (each its own mesh/host in production, its
own ``ReplicaServer`` here) behind a ``FleetRouter`` that fronts one
traffic stream.  The wire is the shared RPC substrate extracted from
the PR-15 master transport (``distributed/transport.py``): newline-
delimited JSON over TCP, typed in-band errors, seeded-backoff retries,
and the client-minted ``client``+``rid`` exactly-once dedup window —
so a replica that executed a request whose response line was lost
REPLAYS the recorded response on retry instead of running the request
twice.

``ReplicaServer`` is the door onto one registry: ``infer`` /
``generate`` / ``status`` / ``metrics`` over the wire, numpy arrays
and LoDTensors codec'd losslessly (``__nd__`` / ``__lod__`` envelopes,
dtype + shape pinned).  Every response piggybacks a load report —
the registry's cheap ``queue_depths()`` sum — so the router's view of
replica load refreshes on the traffic itself, no polling lane.
Registry refusals (``OverloadedError``) cross the wire TYPED, with the
``retry_after_s`` hint attached, and are re-minted on the client side.

``FleetRouter`` dispatch:

* **Balance** — replica score is ``(reported_depth + in_flight + 1) *
  service_time_estimate``; each replica carries its own
  ``ServiceTimeProfile`` fed by observed RPC walls, so a replica that
  is slower (cold caches, worse bucketing) naturally receives less
  offered load than its queue depth alone would suggest.
* **Affinity** — a generation request with a ``session=`` key PINS the
  replica holding its decode state (``SlotStateCache`` slots): every
  subsequent generate on that session lands on the same replica, while
  plain forward lots float freely to the least-loaded replica.  A
  pinned session migrates only when its replica DIES — never for load.
* **Overload** — a single saturated replica is routed around (its
  typed refusal excludes it for that dispatch); when EVERY live
  replica refuses, the router raises the fleet-level
  ``OverloadedError`` with the smallest ``retry_after_s`` any replica
  offered.  A pinned session's refusal is final for that request —
  migrating decode state for load would pay a re-prefill to dodge a
  queue.
* **Failure** — a dead replica (connect/retry budget exhausted on the
  resilient client) is marked and excluded; its in-flight requests are
  re-dispatched to a survivor as NEW logical calls (fresh rid — the
  dead replica's dedup window is gone with it).  For a generation this
  is a RE-PREFILL: greedy decode is deterministic, so the survivor's
  token stream is identical to what the dead replica would have
  produced.  Chaos-tested with the seeded ``FaultInjector`` exactly
  like PR 15's master kill: scripted lost responses exercise the dedup
  replay, a mid-stream ``ReplicaServer.close()`` exercises failover,
  and the gate asserts zero lost / zero duplicated responses and
  token-identical output vs the fault-free single-registry run.
"""

import itertools
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..distributed.transport import (
    RetryPolicy, ResilientServiceClient, ServiceServer,
    DedupWindow, ServiceUnavailableError, ServiceProtocolError)
from .errors import OverloadedError, DeadlineExceededError
from .profile import ServiceTimeProfile

__all__ = ['ReplicaServer', 'FleetRouter', 'FleetFuture']

# router-side logical-call names that mutate replica state (claim a
# queue slot, run a decode) — the resilient client mints a dedup rid
# for these so a retried request is executed exactly once
_FLEET_MUTATING = frozenset(['infer', 'generate'])


# ---------------------------------------------------------------------------
# wire codec — numpy arrays and LoDTensors over the JSON line protocol
# ---------------------------------------------------------------------------

def _wire_encode(v):
    """Lossless JSON envelope for feed/fetch values: ndarray ->
    ``__nd__`` (dtype + shape pinned — a (0, 4) empty or a float32
    round-trips exactly), LoDTensor -> ``__lod__`` (level-of-detail
    offsets ride along)."""
    if isinstance(v, np.ndarray):
        return {'__nd__': {'dtype': str(v.dtype),
                           'shape': list(v.shape),
                           'data': v.ravel().tolist()}}
    if hasattr(v, 'lod') and hasattr(v, 'numpy'):  # fluid LoDTensor
        arr = np.asarray(v.numpy())
        return {'__lod__': {'dtype': str(arr.dtype),
                            'shape': list(arr.shape),
                            'data': arr.ravel().tolist(),
                            'lod': [list(l) for l in v.lod()]}}
    if isinstance(v, dict):
        return {k: _wire_encode(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_wire_encode(x) for x in v]
    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return v.item()
    return v


def _wire_decode(v):
    if isinstance(v, dict):
        if '__nd__' in v and len(v) == 1:
            d = v['__nd__']
            return np.asarray(d['data'], dtype=d['dtype']) \
                .reshape(d['shape'])
        if '__lod__' in v and len(v) == 1:
            d = v['__lod__']
            arr = np.asarray(d['data'], dtype=d['dtype']) \
                .reshape(d['shape'])
            from ..fluid import core  # lazy: codec is import-light
            return core.LoDTensor(arr, [list(l) for l in d['lod']])
        return {k: _wire_decode(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_wire_decode(x) for x in v]
    return v


def _jsonable(v):
    """Best-effort JSON projection for status/metrics payloads (numpy
    scalars -> python, arrays -> lists, opaque objects -> str)."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    return str(v)


# ---------------------------------------------------------------------------
# replica side
# ---------------------------------------------------------------------------

class ReplicaServer(object):
    """One fleet replica: a ``ModelRegistry`` served over the shared
    RPC substrate.

    Methods on the wire: ``infer`` (submit + wait, outputs codec'd),
    ``generate`` (submit_generate + wait, token ids), ``status``,
    ``metrics``, ``load_report``.  Mutating methods arrive with
    ``client``+``rid`` from the router's resilient clients and route
    through a standalone ``DedupWindow`` whose critical section does
    NOT hold the window lock while the registry runs — a long decode
    dedups without serializing the replica (only a RETRY of that same
    rid waits on its in-progress marker, then replays).

    Registry refusals cross typed: ``OverloadedError`` becomes
    ``{'error': ..., 'etype': 'OverloadedError', 'retry_after_s': ...}``
    so the router can route around one hot replica and re-mint the
    typed error when the whole fleet is saturated.  Every response
    carries ``'load': {'depth': N}`` (sum of the registry's per-model
    queue depths) — the router's freshness-on-traffic load feed.
    """

    def __init__(self, registry, host='127.0.0.1', port=0,
                 fault_injector=None, result_timeout_s=120.0,
                 dedup_window=64, dedup_clients=64):
        self.registry = registry
        self.fault_injector = fault_injector
        self.result_timeout_s = float(result_timeout_s)
        self._dedup = DedupWindow(window=dedup_window,
                                  clients=dedup_clients)
        self._m = {'infers': 0, 'generates': 0, 'overloads': 0}
        self._mlock = threading.Lock()
        self._closed = False
        self._srv = ServiceServer(self._dispatch, host=host, port=port,
                                  fault_injector=fault_injector,
                                  dedup_execute=self._dedup.execute)
        self.host, self.port = self._srv.host, self._srv.port

    @property
    def endpoint(self):
        return self._srv.endpoint

    @property
    def closed(self):
        return self._closed

    def _load(self):
        try:
            depths = self.registry.queue_depths()
        except Exception:
            depths = {}
        return {'depth': int(sum(depths.values()))}

    def _count(self, key):
        with self._mlock:
            self._m[key] += 1

    def _dispatch(self, method, req):
        try:
            if method == 'infer':
                fut = self.registry.submit(
                    req['model'], _wire_decode(req['feed']),
                    priority=int(req.get('priority') or 0),
                    deadline_ms=req.get('deadline_ms'))
                outs = fut.result(
                    timeout=req.get('timeout') or self.result_timeout_s)
                self._count('infers')
                return {'outputs': [_wire_encode(np.asarray(o))
                                    for o in outs],
                        'load': self._load()}
            if method == 'generate':
                fut = self.registry.submit_generate(
                    req['model'], _wire_decode(req['feed']),
                    max_len=req.get('max_len'),
                    priority=int(req.get('priority') or 0),
                    deadline_ms=req.get('deadline_ms'))
                tokens = fut.result(
                    timeout=req.get('timeout') or self.result_timeout_s)
                self._count('generates')
                return {'tokens': [int(t) for t in
                                   np.asarray(tokens).ravel()],
                        'load': self._load()}
        except OverloadedError as e:
            # typed refusal, recorded by the dedup window like any
            # response — a replayed refusal is still a refusal
            self._count('overloads')
            return {'error': str(e), 'etype': 'OverloadedError',
                    'model': e.model,
                    'queue_depth': int(e.queue_depth),
                    'retry_after_s': float(e.retry_after_s),
                    'load': self._load()}
        except DeadlineExceededError as e:
            return {'error': str(e), 'etype': 'DeadlineExceededError',
                    'deadline_ms': e.deadline_ms,
                    'late_by_ms': e.late_by_ms,
                    'load': self._load()}
        if method == 'status':
            return {'status': _jsonable(self.registry.status()),
                    'load': self._load()}
        if method == 'metrics':
            with self._mlock:
                served = dict(self._m)
            served['dedup_replays'] = self._dedup.replays
            return {'metrics': _jsonable(self.registry.metrics()),
                    'served': served, 'load': self._load()}
        if method == 'load_report':
            return {'load': self._load()}
        return {'error': 'unknown method %r' % method,
                'etype': 'ValueError'}

    def close(self):
        """Stop serving (the chaos harness's replica kill — the
        registry itself is owned by the caller and stays up)."""
        if not self._closed:
            self._closed = True
            self._srv.close()


# ---------------------------------------------------------------------------
# router side
# ---------------------------------------------------------------------------

class FleetFuture(object):
    """Handle for one fleet request.  Satisfies the loadgen future
    contract: ``result(timeout)``, ``latency_s`` (set on success),
    ``breakdown()``.  ``replica`` is the index that ultimately served
    the request (after any failover)."""

    def __init__(self, kind, model):
        self.kind = kind
        self.model = model
        self.replica = None
        self.latency_s = None
        self.redispatches = 0
        self._done = threading.Event()
        self._result = None
        self._exc = None
        self._t0 = time.time()

    def _finish(self, result=None, exc=None):
        self._result, self._exc = result, exc
        if exc is None:
            self.latency_s = time.time() - self._t0
        self._done.set()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                'fleet %s request not done within %r s'
                % (self.kind, timeout))
        if self._exc is not None:
            raise self._exc
        return self._result

    def breakdown(self):
        return {'replica': self.replica,
                'redispatches': self.redispatches,
                'latency_s': self.latency_s}


class _Replica(object):
    """Router-side state for one replica endpoint: liveness, the
    piggybacked load report, a service-time profile fed by observed
    RPC walls, and an idle-client pool (one resilient client is one
    socket with strict request/response framing — concurrent
    dispatches each check out their own)."""

    def __init__(self, idx, endpoint):
        self.idx = idx
        self.endpoint = endpoint
        self.dead = False
        self.death_reason = None
        self.reported_depth = 0
        self.inflight = 0
        self.dispatches = 0
        self.overloads = 0
        self.profile = ServiceTimeProfile()
        self._idle = []
        self._serial = itertools.count()
        self.lock = threading.Lock()

    def checkout(self, make_client):
        with self.lock:
            if self._idle:
                return self._idle.pop()
            serial = next(self._serial)
        return make_client(self, serial)

    def checkin(self, client):
        with self.lock:
            if not self.dead and not client.closed:
                self._idle.append(client)
                return
        client.close()

    def drain(self):
        with self.lock:
            idle, self._idle = self._idle, []
        for c in idle:
            c.close()


class FleetRouter(object):
    """Replica-aware dispatch over a fleet of ``ReplicaServer``
    endpoints — load-balanced, session-affine, overload-typed,
    failure-riding.  See the module docstring for the policy; the
    surface mirrors ``ModelRegistry`` (``submit`` / ``infer`` /
    ``submit_generate`` / ``generate`` / ``status`` / ``metrics``) so
    the load generator and callers target either interchangeably.

    ``replicas`` may be ``ReplicaServer`` instances or ``host:port``
    endpoint strings.  ``fault_injectors`` optionally maps replica
    index -> ``FaultInjector`` wired into that replica's CLIENT-side
    sites (``client_send``/``client_recv``) for chaos runs.
    """

    def __init__(self, replicas, retry=None, timeout=120.0,
                 max_workers=16, client_id=None,
                 fault_injectors=None, session_log_bound=256):
        if not replicas:
            raise ValueError('FleetRouter: need at least one replica')
        endpoints = [r.endpoint if hasattr(r, 'endpoint') else str(r)
                     for r in replicas]
        self._replicas = [_Replica(i, ep)
                          for i, ep in enumerate(endpoints)]
        self._retry = retry or RetryPolicy()
        self._timeout = float(timeout)
        self._client_id = client_id or ('fleet-%06x' % (id(self) & 0xffffff))
        self._fault_injectors = dict(fault_injectors or {})
        self._lock = threading.Lock()
        self._affinity = {}            # session -> replica idx
        self._session_log = OrderedDict()  # session -> [idx, ...]
        self._session_log_bound = int(session_log_bound)
        self._m = {'dispatches': 0, 'failovers': 0, 're_prefills': 0,
                   'replica_deaths': 0, 'fleet_overloads': 0,
                   'routed_around_overload': 0}
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix='fleet-router')
        self._closed = False

    # -- client plumbing ---------------------------------------------------

    def _make_client(self, rep, serial):
        t = self._retry
        retry = RetryPolicy(
            max_attempts=t.max_attempts, base_backoff_s=t.base_backoff_s,
            max_backoff_s=t.max_backoff_s, deadline_s=t.deadline_s,
            jitter=t.jitter, seed=t.seed + 1009 * rep.idx + serial)
        return ResilientServiceClient(
            [rep.endpoint], retry=retry, timeout=self._timeout,
            fault_injector=self._fault_injectors.get(rep.idx),
            client_id='%s-r%d-c%d' % (self._client_id, rep.idx, serial),
            mutating=_FLEET_MUTATING, service='replica')

    def _rpc(self, rep, method, **kw):
        cli = rep.checkout(self._make_client)
        try:
            resp = cli.call(method, **kw)
        except ServiceUnavailableError:
            cli.close()
            raise
        except ServiceProtocolError:
            rep.checkin(cli)  # in-band refusal; the socket is fine
            raise
        rep.checkin(cli)
        return resp

    # -- dispatch policy ---------------------------------------------------

    def _score(self, rep, sig):
        est = rep.profile.estimate(sig)
        if est is None:
            # optimistic start: an unprobed (replica, signature) pair
            # scores best-possible, so every replica gets explored
            # before measured estimates drive the balance — otherwise
            # the first-probed replica's sub-millisecond estimate
            # beats the fallback forever and monopolizes the stream
            est = rep.profile.floor() or 1e-4
        return (rep.reported_depth + rep.inflight + 1) * max(est, 1e-4)

    def _pick(self, kind, model, session, exclude):
        """Choose a replica under the router lock.  Returns
        (replica, pinned): pinned=True means session affinity chose it
        (an overload there is final, not routed around)."""
        sig = (kind, model)
        affine = session is not None and kind == 'generate'
        with self._lock:
            rep, pinned = None, False
            if affine:
                idx = self._affinity.get(session)
                if idx is not None:
                    cand = self._replicas[idx]
                    if not cand.dead and idx not in exclude:
                        rep, pinned = cand, True
                    # else: pinned replica is gone — re-pin below
            if rep is None:
                best, best_score = None, None
                for r in self._replicas:
                    if r.dead or r.idx in exclude:
                        continue
                    score = self._score(r, sig)
                    if best is None or score < best_score or \
                            (score == best_score
                             and r.dispatches < best.dispatches):
                        best, best_score = r, score
                rep = best
                if rep is not None and affine:
                    self._affinity[session] = rep.idx
            if rep is not None:
                rep.inflight += 1
                rep.dispatches += 1
                self._m['dispatches'] += 1
                if affine:
                    log = self._session_log.get(session)
                    if log is None:
                        while len(self._session_log) >= \
                                self._session_log_bound:
                            self._session_log.popitem(last=False)
                        log = self._session_log[session] = []
                    log.append(rep.idx)
            return rep, pinned

    def _mark_dead(self, rep, reason):
        with self._lock:
            first = not rep.dead
            rep.dead = True
            rep.death_reason = str(reason)
            if first:
                self._m['replica_deaths'] += 1
        rep.drain()

    def _observe(self, rep, sig, wall_s, resp):
        rep.profile.observe(sig, wall_s)
        load = resp.get('load')
        if isinstance(load, dict) and 'depth' in load:
            rep.reported_depth = int(load['depth'])

    def _overload_from(self, model, resp):
        return OverloadedError(
            model, int(resp.get('queue_depth') or 0), 0.0,
            float(resp.get('retry_after_s') or 0.05))

    def _dispatch(self, fut, kind, model, payload, session):
        """Worker: route one logical request to completion — balance,
        route around single-replica overload, fail over on replica
        death (re-dispatch = fresh rid on a survivor; for a generate
        that's the deterministic re-prefill)."""
        sig = (kind, model)
        overloaded = {}    # idx -> retry_after_s hint
        dead_tried = set()
        while True:
            rep, pinned = self._pick(
                kind, model, session,
                exclude=set(overloaded) | dead_tried)
            if rep is None:
                with self._lock:
                    alive = [r for r in self._replicas if not r.dead]
                if alive and overloaded:
                    with self._lock:
                        self._m['fleet_overloads'] += 1
                    depth = max(r.reported_depth for r in alive)
                    raise OverloadedError(model, depth, 0.0,
                                          min(overloaded.values()))
                with self._lock:
                    n_dead = len(dead_tried | {
                        r.idx for r in self._replicas if r.dead})
                raise ServiceUnavailableError(
                    'no live fleet replica for %r (%d dead)'
                    % (model, n_dead))
            fut.replica = rep.idx
            t0 = time.time()
            try:
                resp = self._rpc(rep, kind, model=model, **payload)
            except ServiceUnavailableError as e:
                self._mark_dead(rep, e)
                dead_tried.add(rep.idx)
                fut.redispatches += 1
                with self._lock:
                    self._m['failovers'] += 1
                    if kind == 'generate':
                        self._m['re_prefills'] += 1
                    if session is not None and \
                            self._affinity.get(session) == rep.idx:
                        del self._affinity[session]
                continue
            except ServiceProtocolError as e:
                etype = (getattr(e, 'resp', None) or {}).get('etype')
                if etype == 'OverloadedError':
                    with self._lock:
                        rep.overloads += 1
                    if pinned:
                        # the pinned replica's refusal is final: decode
                        # state doesn't migrate for load
                        raise self._overload_from(model, e.resp)
                    overloaded[rep.idx] = float(
                        e.resp.get('retry_after_s') or 0.05)
                    with self._lock:
                        self._m['routed_around_overload'] += 1
                    continue
                if etype == 'DeadlineExceededError':
                    r = e.resp
                    raise DeadlineExceededError(
                        deadline_ms=r.get('deadline_ms'),
                        late_by_ms=r.get('late_by_ms'),
                        where='fleet') from e
                raise
            finally:
                with self._lock:
                    rep.inflight -= 1
            self._observe(rep, sig, time.time() - t0, resp)
            if kind == 'infer':
                return [_wire_decode(o) for o in resp['outputs']]
            return np.asarray(resp['tokens'], dtype=np.int64)

    def _submit(self, kind, model, payload, session):
        if self._closed:
            raise RuntimeError('FleetRouter is closed')
        fut = FleetFuture(kind, model)

        def worker():
            try:
                res = self._dispatch(fut, kind, model, payload, session)
            except BaseException as e:
                fut._finish(exc=e)
            else:
                fut._finish(result=res)

        self._pool.submit(worker)
        return fut

    # -- public surface (mirrors ModelRegistry) ---------------------------

    def submit(self, model, feed, return_numpy=True, priority=0,
               deadline_ms=None, timeout=None):
        """Async forward: returns a ``FleetFuture`` resolving to the
        list of output arrays.  Forward lots float freely — each
        dispatch picks the best-scored live replica."""
        if not return_numpy:
            raise ValueError('FleetRouter.submit: outputs cross a '
                             'wire — return_numpy=False unsupported')
        payload = {'feed': _wire_encode(feed), 'priority': int(priority),
                   'deadline_ms': deadline_ms,
                   'timeout': timeout or self._timeout}
        return self._submit('infer', model, payload, session=None)

    def infer(self, model, feed, return_numpy=True, timeout=None):
        return self.submit(model, feed, return_numpy=return_numpy,
                           timeout=timeout).result(
                               timeout or self._timeout)

    def submit_generate(self, model, feed, max_len=None, priority=0,
                        deadline_ms=None, timeout=None, session=None):
        """Async generation: resolves to the int64 token-id array.
        ``session`` pins all generates sharing the key to one replica
        (the decode-state affinity); omitted, each generate floats."""
        payload = {'feed': _wire_encode(feed), 'max_len': max_len,
                   'priority': int(priority),
                   'deadline_ms': deadline_ms,
                   'timeout': timeout or self._timeout}
        return self._submit('generate', model, payload, session=session)

    def generate(self, model, feed, max_len=None, timeout=None,
                 session=None):
        return self.submit_generate(model, feed, max_len=max_len,
                                    timeout=timeout,
                                    session=session).result(
                                        timeout or self._timeout)

    def status(self):
        """Fleet status: per-replica liveness + the replica's own
        ``status()`` fetched over the wire for live replicas."""
        out = {}
        for rep in self._replicas:
            if rep.dead:
                out[rep.idx] = {'dead': True,
                                'reason': rep.death_reason}
                continue
            try:
                resp = self._rpc(rep, 'status')
            except ServiceUnavailableError as e:
                self._mark_dead(rep, e)
                out[rep.idx] = {'dead': True, 'reason': str(e)}
                continue
            out[rep.idx] = {'dead': False,
                            'depth': resp['load']['depth'],
                            'status': resp['status']}
        return out

    def metrics(self):
        """Router-local counters — no RPCs.  ``replicas`` carries the
        per-replica dispatch/overload/liveness view the perf gate's
        affinity and failover asserts read."""
        with self._lock:
            m = dict(self._m)
            m['replicas'] = {
                rep.idx: {'endpoint': rep.endpoint, 'dead': rep.dead,
                          'dispatches': rep.dispatches,
                          'overloads': rep.overloads,
                          'reported_depth': rep.reported_depth}
                for rep in self._replicas}
            m['sessions'] = len(self._affinity)
        return m

    def session_dispatches(self):
        """Per-session dispatch log (bounded): session -> ordered list
        of replica indices its generates were dispatched to.  The
        structural affinity assert: fault-free, each list holds ONE
        distinct index; with one replica kill, at most two."""
        with self._lock:
            return {s: list(log)
                    for s, log in self._session_log.items()}

    def close(self):
        self._closed = True
        for rep in self._replicas:
            rep.drain()
        self._pool.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
