"""Per-executable-signature service-time profiles (ISSUE 9).

PR 8's shed horizon was ONE global number — 3x the minimum of the last
8 dispatch walls, whatever signature those dispatches served.  Under a
mixed-shape stream that is exactly wrong in both directions: a cheap
signature's wall drags the global minimum down, so a request of an
expensive signature is ADMITTED toward a deadline it can never meet
(and then served late, displacing live work); symmetrically, one
expensive signature can push a mean-based estimate up and shed cheap
requests that would have made it.  This profile keeps the estimate
PER SIGNATURE: the engine observes every drained dispatch's raw
issue->sync wall keyed by the lot's coalescing signature, and the
MicroBatcher's shed horizon asks for the estimate of each pending
request's OWN signature.

Statistic: the horizon estimate is the MINIMUM of a small recent-wall
window per signature — the same poisoning-proof statistic the global
horizon used (PR 8, measured: a mean never recovers from a
compile-heavy cold dispatch because total shed stops drains; min
bounds the true service floor).  An EWMA of the walls rides along for
observability (``snapshot()``) and as the smoothed 'typical' wall —
it is deliberately NOT the shed statistic.

Seeding: a signature that has never been OBSERVED can still carry a
seed estimate derived from the PR 6 cost registry (XLA cost-analysis
FLOPs over the engine's achieved FLOPs/s) — the engine seeds on the
first drain that carries a cost entry, so the min-window never
bottoms out at a compile-polluted first wall.  Observed walls always
participate alongside the seed; the seed is just one more candidate
floor.
"""

import threading
from collections import deque

__all__ = ['ServiceTimeProfile']


class ServiceTimeProfile(object):
    """EWMA + min-window wall-time profile keyed by executable
    signature.  Thread-safe: the submit path (shed horizon) reads while
    the worker observes.  Bounded: at most ``max_signatures`` entries,
    least-recently-observed evicted first."""

    def __init__(self, window=8, alpha=0.25, max_signatures=64):
        if int(window) < 1:
            raise ValueError('ServiceTimeProfile: window must be >= 1')
        if not (0.0 < float(alpha) <= 1.0):
            raise ValueError('ServiceTimeProfile: alpha must be in '
                             '(0, 1]')
        self._window = int(window)
        self._alpha = float(alpha)
        self._max = int(max_signatures)
        self._lock = threading.Lock()
        # key -> {'walls': deque, 'ewma': float|None, 'seed': float|None,
        #         'n': int}
        self._entries = {}

    def _entry_locked(self, key):
        e = self._entries.pop(key, None)
        if e is None:
            e = {'walls': deque(maxlen=self._window), 'ewma': None,
                 'seed': None, 'n': 0}
            while len(self._entries) >= self._max:
                # dict order is insertion order; pop/reinsert on touch
                # makes the first key the least recently observed
                self._entries.pop(next(iter(self._entries)))
        self._entries[key] = e
        return e

    def seed(self, key, seconds):
        """Install a cost-registry-derived estimate for ``key`` if it
        has none yet.  Seeds never overwrite an existing seed or any
        observation — they exist to pre-date the first (possibly
        compile-polluted) wall, not to fight the measurements."""
        seconds = float(seconds)
        if seconds <= 0.0:
            return False
        with self._lock:
            e = self._entry_locked(key)
            if e['seed'] is not None or e['n']:
                return False
            e['seed'] = seconds
            return True

    def observe(self, key, seconds):
        """One dispatch's raw issue->sync wall for ``key``."""
        seconds = max(float(seconds), 0.0)
        with self._lock:
            e = self._entry_locked(key)
            e['walls'].append(seconds)
            e['n'] += 1
            e['ewma'] = (seconds if e['ewma'] is None else
                         (1.0 - self._alpha) * e['ewma'] +
                         self._alpha * seconds)

    def estimate(self, key):
        """The service-floor estimate for ``key`` in seconds — the min
        of the recent-wall window (and the cost seed, if any), the
        statistic the shed horizon multiplies.  None when the signature
        was never seen."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            cands = list(e['walls'])
            if e['seed'] is not None:
                cands.append(e['seed'])
            return min(cands) if cands else None

    def floor(self):
        """The global fallback: the smallest per-signature estimate —
        what an UNSEEN signature gets (exactly PR 8's global-horizon
        behavior, so the profile only ever sharpens).  None when
        nothing was ever observed or seeded."""
        with self._lock:
            best = None
            for e in self._entries.values():
                cands = list(e['walls'])
                if e['seed'] is not None:
                    cands.append(e['seed'])
                if cands:
                    m = min(cands)
                    best = m if best is None else min(best, m)
            return best

    def signatures(self):
        with self._lock:
            return len(self._entries)

    def snapshot(self):
        """Observability: per-signature estimate/EWMA/count, keyed by a
        bounded repr of the signature plus a hash suffix — engine
        coalescing signatures are long tuples that can share a
        120-char prefix (e.g. differing only in a trailing rung), and
        a bare truncation would silently merge exactly the mixed-shape
        entries the profile exists to tell apart."""
        with self._lock:
            out = {}
            for key, e in self._entries.items():
                cands = list(e['walls'])
                if e['seed'] is not None:
                    cands.append(e['seed'])
                r = repr(key)
                if len(r) > 120:
                    r = '%s#%08x' % (r[:111], hash(key) & 0xffffffff)
                out[r] = {
                    'est_ms': (round(min(cands) * 1e3, 3)
                               if cands else None),
                    'ewma_ms': (round(e['ewma'] * 1e3, 3)
                                if e['ewma'] is not None else None),
                    'seeded': e['seed'] is not None,
                    'observed': e['n'],
                }
            return out
