"""Engine observability: counters, gauges, and a latency reservoir.

The snapshot is the serving analog of the Executor's ``compile_count``:
every number a capacity planner needs to see whether the engine is
batching well (fill ratio), keeping up (queue depth, p99), and staying
inside its compile budget (dispatches vs compiles).  ``fluid.profiler``
surfaces the same snapshot through its ``.events.json`` sidecar (the
engine registers itself as a metrics source), so ``tools/timeline.py``
renders serving spans next to the executor/device slices.
"""

import threading
import time
from collections import deque

__all__ = ['EngineMetrics', 'RateWindow']


def _percentile(sorted_vals, p):
    if not sorted_vals:
        return None
    idx = min(int(len(sorted_vals) * p), len(sorted_vals) - 1)
    return sorted_vals[idx]


class RateWindow(object):
    """Events-per-second over a sliding window of recent event
    timestamps (ISSUE 9) — the adaptive admission watermarks compare
    an engine's request ARRIVAL rate against its delivery DRAIN rate.
    A timestamp window, not a decaying counter: an idle engine's rate
    goes to zero instead of freezing at its last busy value."""

    def __init__(self, maxlen=128, horizon_s=10.0):
        self._times = deque(maxlen=int(maxlen))
        self._horizon_s = float(horizon_s)
        self._lock = threading.Lock()

    def note(self, n=1):
        now = time.time()
        with self._lock:
            for _ in range(int(n)):
                self._times.append(now)

    def rate(self):
        """Events/s over the retained window clipped to the horizon;
        None before the second event (one timestamp spans no time).
        The inter-arrival estimator (n-1 events over the span from the
        first timestamp): n/span would overestimate by n/(n-1) —
        2x at n=2, exactly the small-count regime a falling-behind
        engine's drain window sits in, which would inflate the
        drain/arrival ratio and delay door-shedding."""
        now = time.time()
        with self._lock:
            times = [t for t in self._times
                     if now - t <= self._horizon_s]
            if len(times) < 2:
                return None
            span = max(now - times[0], 1e-6)
            return (len(times) - 1) / span


class EngineMetrics(object):
    """Thread-safe counters shared by the submit path and the worker.

    Latencies keep the last ``reservoir`` request round trips (enqueue
    to delivery), enough for stable p50/p99 without unbounded growth.
    """

    def __init__(self, reservoir=2048):
        self._lock = threading.Lock()
        self._latencies = deque(maxlen=reservoir)
        self.requests = 0
        self.rows = 0
        self.lots = 0
        self.padded_rows = 0
        self.bucket_rows = 0
        self.deadline_flushes = 0
        self.full_flushes = 0
        self.dispatches = 0
        self.steps_dispatched = 0
        self.compiles = 0
        self.errors = 0
        # SLO lane (ISSUE 8): requests shed past-deadline instead of
        # served late — the deadline scheduler's drop counter (typed
        # DeadlineExceededError on the future; NOT counted as errors)
        self.shed = 0
        # trailing-dim bucketing (ISSUE 5): padded vs real CELLS along
        # bucketed trailing axes (weighted by rows, summed over feeds)
        self.trailing_real_cells = 0
        self.trailing_padded_cells = 0
        # request tracing (ISSUE 6): per-stage seconds summed over
        # delivered traced requests — the aggregate view of the
        # per-request breakdowns (queue/pad/arbitration/dispatch/
        # device/trim)
        self.stage_s = {}
        self.traced_requests = 0
        # cost accounting (ISSUE 6): XLA cost-analysis FLOPs executed
        # vs wall seconds of the drained dispatches that carried a cost
        # entry — achieved-MFU's numerator/denominator
        self.device_flops = 0.0
        self.device_seconds = 0.0
        # generation lane (ISSUE 7): continuous-batching decode.
        # decode_tokens counts REAL emitted tokens (alive slot-steps);
        # decode_slot_steps counts K*S scan capacity — their ratio is
        # the slot occupancy the admission policy achieved.
        self.decode_requests = 0
        self.decode_finished = 0
        self.decode_dispatches = 0
        self.decode_scan_steps = 0
        self.decode_tokens = 0
        self.decode_slot_steps = 0
        self.prefill_lots = 0
        # pipelined decode (ISSUE 9): host-sync accounting.  A HOST
        # SYNC is a harvest that blocked with NO other scan in flight
        # behind it — the device sat idle while the host round-tripped
        # (the per-scan-sync lane pays one per scan; the chained lane
        # pays one per chain FLUSH).  harvests counts every token-block
        # materialization; chain_flushes counts the admission/eviction/
        # shed boundaries that drained the whole chain.
        self.decode_host_syncs = 0
        self.decode_harvests = 0
        self.decode_chain_flushes = 0
        # chunked prefill (ISSUE 14): chunk dispatches + prompt tokens
        # they consumed, and the decode inter-token stall gauge — the
        # max wall gap between consecutive token-block harvests while
        # prefill work was in flight, raw seconds and in units of the
        # lane's min scan wall ("step boundaries missed to a prompt")
        self.prefill_chunks = 0
        self.prefill_chunk_tokens = 0
        self.max_decode_stall_cycles = 0.0
        self.max_decode_stall_s = 0.0

    def note_request(self, rows):
        with self._lock:
            self.requests += 1
            self.rows += int(rows)

    def note_lot(self, real_rows, bucket_rows, deadline_flush):
        with self._lock:
            self.lots += 1
            self.bucket_rows += int(bucket_rows)
            self.padded_rows += int(bucket_rows) - int(real_rows)
            if deadline_flush:
                self.deadline_flushes += 1
            else:
                self.full_flushes += 1

    def note_trailing(self, real_cells, padded_cells):
        """One request's trailing-dim padding tax: real vs padded cells
        (extent x rows, summed over that request's bucketed feed axes).
        The snapshot derives the padding-waste ratio from the totals."""
        with self._lock:
            self.trailing_real_cells += int(real_cells)
            self.trailing_padded_cells += int(padded_cells)

    def note_dispatch(self, steps, compiles):
        with self._lock:
            self.dispatches += 1
            self.steps_dispatched += int(steps)
            self.compiles += int(compiles)

    def note_latency(self, seconds):
        with self._lock:
            self._latencies.append(float(seconds))

    def note_error(self):
        with self._lock:
            self.errors += 1

    def note_shed(self):
        with self._lock:
            self.shed += 1

    def note_stages(self, stage_s):
        """One delivered request's finalized per-stage seconds."""
        with self._lock:
            self.traced_requests += 1
            for stage, s in stage_s.items():
                self.stage_s[stage] = self.stage_s.get(stage, 0.0) + \
                    float(s)

    def note_generate(self):
        with self._lock:
            self.decode_requests += 1

    def note_prefill_lot(self):
        with self._lock:
            self.prefill_lots += 1

    def note_decode_dispatch(self, scan_steps, alive_slot_steps,
                             slot_steps, finished):
        """One drained decode scan: K scan steps over S slots, of which
        ``alive_slot_steps`` emitted real tokens and ``finished``
        requests hit their stop condition inside the scan."""
        with self._lock:
            self.decode_dispatches += 1
            self.decode_scan_steps += int(scan_steps)
            self.decode_tokens += int(alive_slot_steps)
            self.decode_slot_steps += int(slot_steps)
            self.decode_finished += int(finished)

    def note_decode_harvest(self, blocking):
        """One harvested decode token block (ISSUE 9); ``blocking``
        marks a device-idling host sync (nothing else in flight behind
        the harvested scan)."""
        with self._lock:
            self.decode_harvests += 1
            if blocking:
                self.decode_host_syncs += 1

    def note_decode_flush(self):
        with self._lock:
            self.decode_chain_flushes += 1

    def note_chunk_dispatch(self, tokens):
        """One chunked-prefill dispatch (ISSUE 14) consuming
        ``tokens`` real prompt tokens across the prefilling slots."""
        with self._lock:
            self.prefill_chunks += 1
            self.prefill_chunk_tokens += int(tokens)

    def note_decode_stall(self, cycles, seconds):
        """One observed decode inter-token stall under in-flight
        prefill work (ISSUE 14); the snapshot keeps the max."""
        with self._lock:
            self.max_decode_stall_cycles = max(
                self.max_decode_stall_cycles, float(cycles))
            self.max_decode_stall_s = max(self.max_decode_stall_s,
                                          float(seconds))

    def note_device(self, flops, seconds):
        """One drained dispatch's cost-analysis FLOPs + wall seconds
        (dispatch issue -> host sync) — accumulates achieved MFU."""
        with self._lock:
            self.device_flops += float(flops)
            self.device_seconds += float(seconds)

    def device_rate(self):
        """Achieved FLOPs/s so far (None before any cost-carrying
        drain) — the ServiceTimeProfile seeder's denominator (ISSUE
        9): a signature's cost-analysis FLOPs over this rate is its
        expected wall."""
        with self._lock:
            if self.device_seconds > 0 and self.device_flops > 0:
                return self.device_flops / self.device_seconds
            return None

    def decode_snapshot(self, active_slots=None, free_slots=None,
                        pending=None, inflight_scans=None):
        """The generation lane's block of ``snapshot()`` (None when the
        engine serves no generation model): request/token tallies, the
        amortization ratios (tokens and scan steps per dispatch), the
        occupancy the continuous-batching admission achieved, and the
        pipelined lane's host-sync accounting (ISSUE 9)."""
        with self._lock:
            if not self.decode_requests:
                return None
            return {
                'host_syncs': self.decode_host_syncs,
                'harvests': self.decode_harvests,
                'chain_flushes': self.decode_chain_flushes,
                'inflight_scans': inflight_scans,
                'host_syncs_per_token': (
                    round(self.decode_host_syncs / self.decode_tokens,
                          4)
                    if self.decode_tokens else None),
                'requests': self.decode_requests,
                'finished': self.decode_finished,
                'tokens': self.decode_tokens,
                'dispatches': self.decode_dispatches,
                'prefill_lots': self.prefill_lots,
                'prefill_chunks': self.prefill_chunks,
                'prefill_chunk_tokens': self.prefill_chunk_tokens,
                'max_decode_stall_cycles': (
                    round(self.max_decode_stall_cycles, 3)
                    if self.max_decode_stall_cycles else 0.0),
                'max_decode_stall_s': (
                    round(self.max_decode_stall_s, 6)
                    if self.max_decode_stall_s else 0.0),
                'steps_per_dispatch': (
                    round(self.decode_scan_steps /
                          self.decode_dispatches, 3)
                    if self.decode_dispatches else None),
                'tokens_per_dispatch': (
                    round(self.decode_tokens / self.decode_dispatches,
                          3)
                    if self.decode_dispatches else None),
                'slot_occupancy': (
                    round(self.decode_tokens / self.decode_slot_steps,
                          4)
                    if self.decode_slot_steps else None),
                'active_slots': active_slots,
                'free_slots': free_slots,
                'pending': pending,
            }

    def snapshot(self, queue_depth=0, queue_age=None):
        """One coherent dict: counters plus the derived rates the
        ROADMAP's serving lane cares about (batch fill ratio = real rows
        over padded-bucket rows across all lots; steps/dispatch is the
        measured pipelining depth).  ``queue_age`` is the batcher's
        age_stats() dict (ISSUE 8) — the admission watermarks' inputs,
        surfaced so a stalling queue shows up in metrics() without
        waiting for the watchdog dump."""
        with self._lock:
            lat = sorted(self._latencies)
            return {
                'queue_depth': int(queue_depth),
                'queue_age_oldest_s': (
                    round(queue_age['oldest_s'], 4)
                    if queue_age else None),
                'queue_age_mean_s': (
                    round(queue_age['mean_s'], 4)
                    if queue_age else None),
                'shed': self.shed,
                'requests': self.requests,
                'rows': self.rows,
                'lots': self.lots,
                'dispatches': self.dispatches,
                'steps_dispatched': self.steps_dispatched,
                'steps_per_dispatch': (
                    round(self.steps_dispatched / self.dispatches, 3)
                    if self.dispatches else None),
                'compiles': self.compiles,
                'errors': self.errors,
                'padded_rows': self.padded_rows,
                'batch_fill_ratio': (
                    round((self.bucket_rows - self.padded_rows) /
                          self.bucket_rows, 4)
                    if self.bucket_rows else None),
                'deadline_flushes': self.deadline_flushes,
                'full_flushes': self.full_flushes,
                'trailing_real_cells': self.trailing_real_cells,
                'trailing_padded_cells': self.trailing_padded_cells,
                'trailing_padding_waste': (
                    round(1.0 - self.trailing_real_cells /
                          self.trailing_padded_cells, 4)
                    if self.trailing_padded_cells else None),
                'p50_latency_ms': (
                    round(_percentile(lat, 0.50) * 1e3, 3) if lat else None),
                'p99_latency_ms': (
                    round(_percentile(lat, 0.99) * 1e3, 3) if lat else None),
                'traced_requests': self.traced_requests,
                'stages_ms_mean': ({
                    stage: round(s / self.traced_requests * 1e3, 3)
                    for stage, s in sorted(self.stage_s.items())
                } if self.traced_requests else None),
                'device_flops_per_s': (
                    round(self.device_flops / self.device_seconds, 1)
                    if self.device_seconds > 0 and self.device_flops > 0
                    else None),
            }
