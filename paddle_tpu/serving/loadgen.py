"""Open-loop load generation: drive the serving stack at production
rates and measure the tail (ISSUE 8).

A CLOSED-loop driver (submit, wait, submit) measures the server's
latency at whatever rate the server happens to sustain — under
overload it politely slows down with the server and the tail looks
fine.  Production traffic does not wait: arrivals are an external
process.  This generator is OPEN-loop the way serving papers measure
(Clockwork OSDI '20, ORCA OSDI '22): arrival times are pre-drawn from
a Poisson process at the configured rate, every request fires at its
arrival time whether or not earlier ones completed, and the report
separates THROUGHPUT (completed/s) from GOODPUT (completed INSIDE the
request's deadline) — the number an SLO actually pays for.

Determinism: all randomness (arrival gaps, traffic-class picks, feed
payloads) comes from one seeded RandomState, and feeds are pre-drawn
before the clock starts — so two runs with the same seed offer the
IDENTICAL request stream.  The ``slo`` perf gate leans on this to
drive a deadline-scheduled engine and a FIFO engine with the same
traffic and compare goodput and bitwise results.

    gen = OpenLoopLoadGen(
        reg,
        classes=[TrafficClass(lambda rng: {'x': rng.rand(4, 6).astype('float32')},
                              model='ranker', deadline_ms=50),
                 TrafficClass(make_prompt, model='chat', kind='generate',
                              weight=0.2, deadline_ms=500, max_len=16)],
        rate=200.0, n_requests=1000, seed=0)
    report = gen.run()
    print(report['goodput_req_s'], report['p99_ms'], report['p999_ms'])
"""

import time

import numpy as np

from .errors import DeadlineExceededError, OverloadedError

__all__ = ['TrafficClass', 'OpenLoopLoadGen']


def _pct(sorted_vals, p):
    if not sorted_vals:
        return None
    idx = min(int(len(sorted_vals) * p), len(sorted_vals) - 1)
    return sorted_vals[idx]


class TrafficClass(object):
    """One slice of the offered mix.

    feed_fn: rng -> feed dict (drawn once per request, pre-clock).
    model: registry model name (None when the target is a bare engine).
    kind: 'submit' (forward) or 'generate' (the decode lane).
    weight: relative share of the offered stream.
    deadline_ms / priority: the SLO attached to every request of this
        class (None deadline = never expires; such responses always
        count toward goodput).
    max_len: generation budget for kind='generate'.
    name: report key; defaults to model/kind.
    """

    def __init__(self, feed_fn, model=None, kind='submit', weight=1.0,
                 deadline_ms=None, priority=0, max_len=None, name=None):
        if kind not in ('submit', 'generate'):
            raise ValueError("TrafficClass: kind must be 'submit' or "
                             "'generate', got %r" % (kind, ))
        if float(weight) <= 0:
            raise ValueError('TrafficClass: weight must be > 0')
        self.feed_fn = feed_fn
        self.model = model
        self.kind = kind
        self.weight = float(weight)
        self.deadline_ms = (float(deadline_ms)
                            if deadline_ms is not None else None)
        self.priority = int(priority)
        self.max_len = max_len
        self.name = name or '%s:%s' % (model or 'engine', kind)


class OpenLoopLoadGen(object):
    """Offer a Poisson stream of mixed traffic to ``target`` (a
    ModelRegistry or a single InferenceEngine) and report the tail.

    rate: offered arrivals per second (the Poisson intensity).
    n_requests / duration_s: stream length — an explicit count, or
        rate x duration when only a duration is given.
    seed: the stream's identity — same seed, same arrivals, same
        class picks, same payloads.
    keep_records: retain per-request outcome records (result arrays,
        error instance, trace breakdown) on the report under
        'records' — the slo gate's bitwise-comparison hook.  Off by
        default: a long soak should not hoard every response.
    result_timeout_s: per-future wait bound during collection; a
        future still unresolved then counts as an error (and the
        timeout is itself report-visible — a hung worker must not
        hang the harness).
    retry_overloaded: honor the ``OverloadedError.retry_after_s``
        hint (ISSUE 15 satellite) with ONE bounded re-submit per
        rejected request, scheduled at rejection time + the hint + a
        small seeded jitter (decorrelated resubmits) and fired
        without perturbing the offered arrival times.  The report
        gains ``overload_retries`` (re-submits fired) and
        ``retry_success`` (retried requests that completed) — so the
        harness exercises the documented client contract instead of
        just recording the hint.
    """

    def __init__(self, target, classes, rate, n_requests=None,
                 duration_s=None, seed=0, keep_records=False,
                 result_timeout_s=120.0, retry_overloaded=False):
        if not classes:
            raise ValueError('OpenLoopLoadGen: at least one '
                             'TrafficClass is required')
        if float(rate) <= 0:
            raise ValueError('OpenLoopLoadGen: rate must be > 0 req/s')
        if n_requests is None:
            if duration_s is None:
                raise ValueError('OpenLoopLoadGen: pass n_requests= or '
                                 'duration_s=')
            n_requests = max(int(float(rate) * float(duration_s)), 1)
        self.target = target
        self.classes = list(classes)
        self.rate = float(rate)
        self.n_requests = int(n_requests)
        self.seed = int(seed)
        self.keep_records = bool(keep_records)
        self.result_timeout_s = float(result_timeout_s)
        self.retry_overloaded = bool(retry_overloaded)

    # ---- the stream -----------------------------------------------------

    def _draw(self):
        """Pre-draw the whole stream: arrival offsets, class picks, and
        payloads — before the clock starts, so feed generation cost
        never leaks into the offered timing and the stream is
        identical across targets."""
        rng = np.random.RandomState(self.seed)
        n = self.n_requests
        arrivals = np.cumsum(rng.exponential(1.0 / self.rate, size=n))
        weights = np.asarray([c.weight for c in self.classes])
        picks = rng.choice(len(self.classes), size=n,
                           p=weights / weights.sum())
        feeds = [self.classes[k].feed_fn(rng) for k in picks]
        # seeded retry jitter, drawn LAST so enabling retries leaves
        # the arrival/pick/payload stream bit-identical to a run
        # without them
        jitter = (rng.uniform(0.0, 0.05, size=n)
                  if self.retry_overloaded else None)
        return arrivals, picks, feeds, jitter

    def _fire(self, cls, feed):
        """One submission; returns the future (or raises)."""
        if cls.model is not None:
            if cls.kind == 'generate':
                return self.target.submit_generate(
                    cls.model, feed, max_len=cls.max_len,
                    priority=cls.priority, deadline_ms=cls.deadline_ms)
            return self.target.submit(cls.model, feed,
                                      priority=cls.priority,
                                      deadline_ms=cls.deadline_ms)
        if cls.kind == 'generate':
            return self.target.submit_generate(
                feed, max_len=cls.max_len, priority=cls.priority,
                deadline_ms=cls.deadline_ms)
        return self.target.submit(feed, priority=cls.priority,
                                  deadline_ms=cls.deadline_ms)

    def _fire_due_retries(self, outcomes, feeds, pending, fired,
                          until=None):
        """Fire scheduled overload re-submits whose due time lands
        before ``until`` (None = drain all, sleeping to each due) —
        between arrivals, so the offered stream's timing is never
        perturbed by a retry."""
        pending.sort()
        while pending:
            due, i = pending[0]
            if until is not None and due >= until:
                return
            pending.pop(0)
            delay = due - time.time()
            if delay > 0:
                time.sleep(delay)
            cls = outcomes[i][0]
            fired.add(i)
            try:
                outcomes[i] = (cls, self._fire(cls, feeds[i]), None)
            except Exception as exc:  # still overloaded: final answer
                outcomes[i] = (cls, None, exc)

    def run(self):
        """Offer the stream, collect every outcome, report the tail."""
        arrivals, picks, feeds, retry_jitter = self._draw()
        n = self.n_requests
        outcomes = [None] * n  # (cls, future | None, submit_error)
        retry_pending = []  # (due_t, request index) — one shot each
        retry_fired = set()
        t0 = time.time()
        for i in range(n):
            target = t0 + arrivals[i]
            if retry_pending:
                self._fire_due_retries(outcomes, feeds, retry_pending,
                                       retry_fired, until=target)
            delay = target - time.time()
            if delay > 0:
                # open loop: sleep TO the arrival; when the submitter
                # itself falls behind (a stalled inline dispatch), fire
                # immediately — never skip an arrival
                time.sleep(delay)
            cls = self.classes[picks[i]]
            try:
                outcomes[i] = (cls, self._fire(cls, feeds[i]), None)
            except Exception as exc:  # OverloadedError and friends
                outcomes[i] = (cls, None, exc)
                if self.retry_overloaded and \
                        isinstance(exc, OverloadedError):
                    # the documented client contract: back off for the
                    # server's hint, then ONE re-submit
                    retry_pending.append(
                        (time.time() + max(exc.retry_after_s, 0.0) +
                         retry_jitter[i], i))
        if retry_pending:
            self._fire_due_retries(outcomes, feeds, retry_pending,
                                   retry_fired)
        offered_window = time.time() - t0
        # collection: block on every future (arrival order — the waits
        # overlap, so the bound is per-future, not cumulative)
        records = []
        lat = []
        completed = good = shed = rejected = late = errors = 0
        retry_success = 0
        keep = self.keep_records
        for i in range(n):
            cls, fut, submit_err = outcomes[i]
            # the per-request record (result slices, trace breakdown)
            # is only materialized under keep_records: a long soak
            # must not pay a dict + breakdown build per request just
            # to throw them away
            rec = ({'i': i, 'class': cls.name, 'status': None,
                    'latency_ms': None} if keep else None)
            if keep and i in retry_fired:
                rec['retried'] = True
            err = submit_err
            result = None
            if fut is not None:
                try:
                    result = fut.result(self.result_timeout_s)
                except Exception as exc:
                    err = exc
                if keep:
                    rec['breakdown'] = fut.breakdown()
            if err is None:
                completed += 1
                if i in retry_fired:
                    retry_success += 1
                latency_ms = fut.latency_s * 1e3
                lat.append(latency_ms)
                good_one = (cls.deadline_ms is None or
                            latency_ms <= cls.deadline_ms)
                good += 1 if good_one else 0
                late += 0 if good_one else 1
                if keep:
                    rec['latency_ms'] = round(latency_ms, 3)
                    rec['status'] = 'good' if good_one else 'late'
                    rec['result'] = result
            elif isinstance(err, DeadlineExceededError):
                shed += 1
                if keep:
                    rec['status'] = 'shed'
            elif isinstance(err, OverloadedError):
                rejected += 1
                if keep:
                    rec['status'] = 'rejected'
                    rec['retry_after_s'] = err.retry_after_s
            else:
                errors += 1
                if keep:
                    rec['status'] = 'error'
            if keep:
                rec['error'] = err
                records.append(rec)
        elapsed = time.time() - t0
        lat.sort()
        report = {
            'offered': n,
            'offered_req_s': round(n / max(arrivals[-1], 1e-9), 3),
            'offered_window_s': round(offered_window, 4),
            'elapsed_s': round(elapsed, 4),
            'completed': completed,
            'sustained_req_s': round(completed / max(elapsed, 1e-9), 3),
            # goodput: the SLO number — responses that arrived in time
            'goodput': good,
            'goodput_req_s': round(good / max(elapsed, 1e-9), 3),
            'late': late,
            'shed': shed,
            'overload_rejected': rejected,
            # the retry-the-hint contract (ISSUE 15): one bounded
            # re-submit per overload-rejected request when enabled
            'overload_retries': len(retry_fired),
            'retry_success': retry_success,
            'errors': errors,
            'p50_ms': (round(_pct(lat, 0.50), 3) if lat else None),
            'p99_ms': (round(_pct(lat, 0.99), 3) if lat else None),
            'p999_ms': (round(_pct(lat, 0.999), 3) if lat else None),
            'classes': [c.name for c in self.classes],
            'rate': self.rate,
            'seed': self.seed,
        }
        if self.keep_records:
            report['records'] = records
        return report
