"""Typed serving errors: the SLO lane's reject/shed vocabulary.

Tail-latency serving needs callers to DISTINGUISH outcomes a bare
RuntimeError collapses: a request shed because its deadline passed
(retry is pointless — the answer is already worthless), a request
refused at the door because the model is overloaded (retry after the
hint — the queue will have drained), and a request racing an engine
shutdown (route to another replica).  Clockwork (OSDI '20) and ORCA
(OSDI '22) both make this distinction first-class; the reference Fluid
C-API had only per-call status codes.

All three subclass RuntimeError so pre-SLO callers that caught broad
RuntimeError keep working.
"""

__all__ = ['DeadlineExceededError', 'OverloadedError', 'EngineClosedError']


class DeadlineExceededError(RuntimeError):
    """The request was SHED: its deadline passed (or could not be met
    within the scheduler's service estimate) while it waited, so the
    engine dropped it instead of serving a dead answer late.  Carries
    the deadline and how late the request was at shed time, so load
    generators and callers can account goodput without string
    matching."""

    def __init__(self, trace_id=None, deadline_ms=None, late_by_ms=None,
                 where='queue'):
        self.trace_id = trace_id
        self.deadline_ms = deadline_ms
        self.late_by_ms = late_by_ms
        self.where = where  # 'queue' | 'decode' | 'admit'
        late = ('%+.1f ms past' % late_by_ms
                if late_by_ms is not None else 'past')
        super(DeadlineExceededError, self).__init__(
            'request %s shed at the %s: %s its %s ms deadline — the '
            'response could no longer arrive in time, so serving it '
            'would only have delayed live requests'
            % (trace_id or '<untraced>', where, late,
               deadline_ms if deadline_ms is not None else '?'))


class OverloadedError(RuntimeError):
    """Admission-control reject: the model's queue crossed its
    depth/age watermark, so the registry refused the request at ROUTING
    time instead of letting it queue toward certain deadline death.
    ``retry_after_s`` is the hint a client (or load balancer) should
    back off for — roughly one queue-drain window."""

    def __init__(self, model, queue_depth, queue_age_s, retry_after_s):
        self.model = model
        self.queue_depth = int(queue_depth)
        self.queue_age_s = float(queue_age_s)
        self.retry_after_s = float(retry_after_s)
        super(OverloadedError, self).__init__(
            'model %r is overloaded (queue depth %d, oldest queued '
            'request %.3fs old) — retry after ~%.3fs'
            % (model, self.queue_depth, self.queue_age_s,
               self.retry_after_s))


class EngineClosedError(RuntimeError):
    """The engine (or its micro-batch queue) stopped accepting work —
    a submit raced a stop()/unload().  Typed so a router retrying on a
    replacement replica does not have to pattern-match message text."""
