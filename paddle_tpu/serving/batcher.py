"""Dynamic micro-batching queue: coalesce submitted requests into lots.

The reference serves inference through a per-request C-API call
(paddle_inference_api.h Run); a TPU amortizes its ~100ms tunnel
dispatch by batching.  The queue's contract:

  * a lot closes when its rows reach ``max_batch_size`` (full flush) OR
    the OLDEST waiting request has aged ``max_wait_s`` (deadline flush)
    — latency is bounded by max_wait even at low traffic;
  * only signature-compatible requests (same feed names, BUCKETED
    trailing dims and dtypes — the engine quantizes variable seq-len/
    resolution dims onto its TrailingDimBuckets ladder before the sig
    is taken, so mixed-length requests in one rung DO coalesce)
    coalesce; an incompatible request simply waits its turn as the
    head of a later lot;
  * a lone request larger than max_batch_size forms its own lot (the
    bucket ladder gives it an exact entry) rather than being rejected.

Scheduling (ISSUE 8): under ``scheduling='edf'`` (the default) lot
formation is deadline-aware the way Clockwork (OSDI '20) serves its
SLOs — the head of each lot is the highest-PRIORITY pending request,
earliest-deadline-first within a priority class (requests without a
deadline order after deadlined peers, by arrival); and requests whose
deadline has already passed — or can no longer be met within the
engine's current service estimate — are SHED with a typed
``DeadlineExceededError`` instead of being served late, so an
overloaded queue spends the chip on answers that can still arrive in
time.  Requests carrying neither priority nor deadline degrade to
exact FIFO order, so pre-SLO callers see no change.
``scheduling='fifo'`` restores strict arrival order with no shedding
(the baseline side of the ``slo`` perf gate: under overload it happily
serves already-dead requests, starving live ones).

Requests double as futures: ``submit`` returns an InferenceRequest the
caller blocks on with ``.result()``; the engine's worker thread fills
it after the trimmed fetches come back.
"""

import threading
import time
from collections import deque

from .errors import DeadlineExceededError, EngineClosedError

__all__ = ['InferenceRequest', 'MicroBatcher']


class InferenceRequest(object):
    """One submitted feed dict + its future result.

    ``trailing`` maps a BUCKETED trailing extent back to this request's
    real extent ({padded_T: real_T}, axis 1) when the engine's
    trailing-dim ladder padded the request's seq/resolution dims up to
    a rung — the deliver path trims per-request fetches back to the
    real extents (engine._drain_one).

    ``trace`` is the request's TraceContext (fluid.trace): the engine
    threads ONE trace id from submit() through the micro-batch lot,
    dispatch, device sync and per-request trim, so a delivered request
    answers "where did my latency go" via ``breakdown()``.

    ``kind`` partitions the queue's lot space (ISSUE 7): 'forward'
    requests coalesce into eval lots, 'generate' ones
    (GenerationRequest) into PREFILL lots the engine routes to the
    decode lane — the two kinds never share a lot even if their feed
    signatures collide.

    ``priority`` / ``deadline_ms`` are the SLO lane (ISSUE 8): higher
    priority classes form lots first; within a class the scheduler is
    earliest-deadline-first, and a deadlined request that can no longer
    answer in time is shed with DeadlineExceededError instead of served
    late.  ``deadline_t`` is the ABSOLUTE wall-clock deadline (enqueue
    + deadline_ms); None means the request never expires."""

    kind = 'forward'

    def __init__(self, feed, rows, sig, return_numpy=True, trailing=None,
                 trace=None, priority=0, deadline_ms=None):
        self.feed = feed
        self.rows = rows  # None for unbatchable (LoD / scalar) feeds
        self.sig = sig
        self.trailing = trailing or None
        self.return_numpy = return_numpy
        self.trace = trace
        self.priority = int(priority)
        self.deadline_ms = (float(deadline_ms)
                            if deadline_ms is not None else None)
        self.enqueue_t = time.time()
        self.deadline_t = (self.enqueue_t + self.deadline_ms / 1e3
                           if self.deadline_ms is not None else None)
        self.latency_s = None
        self._event = threading.Event()
        self._result = None
        self._error = None

    @property
    def trace_id(self):
        return self.trace.trace_id if self.trace is not None else None

    def breakdown(self):
        """The per-request stage breakdown (trace id, end-to-end ms,
        stage ms in pipeline order) — populated at delivery; None for a
        request created without a trace context."""
        return self.trace.breakdown() if self.trace is not None else None

    def done(self):
        return self._event.is_set()

    def set_result(self, result):
        self.latency_s = time.time() - self.enqueue_t
        self._result = result
        self._event.set()

    def set_error(self, exc):
        self.latency_s = time.time() - self.enqueue_t
        self._error = exc
        self._event.set()

    def result(self, timeout=None):
        """Block until delivered; re-raises the dispatch's exception."""
        if not self._event.wait(timeout):
            raise TimeoutError('inference request not completed within '
                               '%r s' % (timeout, ))
        if self._error is not None:
            raise self._error
        return self._result


def _sched_key(req, now=None, aging_s=None, max_priority=None):
    """EDF-within-priority: higher priority first, then earliest
    absolute deadline (no deadline = never urgent), then arrival —
    so undeadlined equal-priority traffic keeps exact FIFO order.

    ``aging_s`` is the starvation escape hatch (ISSUE 11 satellite;
    ROADMAP item 5 leftover): strict priority starves a low class
    forever under saturating high-priority traffic, so each full aging
    window a request has waited promotes its EFFECTIVE class by one —
    a request aging ``k * aging_s`` competes as ``priority + k``.
    Promotion engages ONLY for requests below ``max_priority`` (the
    highest REAL class currently pending): starvation needs someone
    above you, and a class alone in the queue must keep pure EDF order
    — an aged undeadlined request must not cut ahead of a
    deadline-imminent peer of its own class.  Real priority is
    untouched; only lot-formation order changes."""
    pr = req.priority
    if aging_s and max_priority is not None and pr < max_priority:
        pr += int((now - req.enqueue_t) / aging_s)
    return (-pr,
            req.deadline_t if req.deadline_t is not None else float('inf'),
            req.enqueue_t)


class MicroBatcher(object):
    """``scheduling``: 'edf' (deadline-aware lot formation + shedding,
    the default — degrades to FIFO for requests without priorities or
    deadlines) or 'fifo' (strict arrival order, nothing shed).

    ``on_shed``: callback invoked (queue lock held) with each shed
    request; the owner errors the future, counts the shed, and marks
    the trace.  When None the batcher errors the future itself.

    ``service_estimate_fn``: optional () -> seconds — the engine's
    current estimate of one dispatch's service time.  A deadlined
    request is shed not just when its deadline HAS passed but when it
    cannot be met within the estimate (Clockwork's admission rule):
    serving a request that will miss anyway only delays live ones.

    ``service_estimate_for``: optional (request) -> seconds — the
    PER-SIGNATURE form of the horizon (ISSUE 9): the engine's
    ServiceTimeProfile answers with the estimate for each request's
    OWN executable signature (falling back to the global floor for an
    unseen one), so a mixed-shape queue sheds the slow-signature
    request a global minimum would have admitted toward certain
    deadline death — and keeps the cheap request the slow signature's
    wall would have doomed.  Takes precedence over
    ``service_estimate_fn`` when both are given.

    ``priority_aging_s``: optional seconds — the starvation escape
    hatch (ISSUE 11 satellite).  Strict priority-first lot formation
    starves a saturated-out low class FOREVER; with aging set, every
    full window a request has waited raises its EFFECTIVE class by one
    for scheduling only, so a starving request eventually outranks
    fresh high-priority arrivals.  Promotion engages only for requests
    BELOW the highest pending real class (cross-class starvation is
    the target; within one class pure EDF order holds).  None
    (default) keeps strict priority; EDF scheduling only."""

    def __init__(self, max_batch_size=32, max_wait_s=0.005,
                 scheduling='edf', on_shed=None,
                 service_estimate_fn=None, service_estimate_for=None,
                 priority_aging_s=None, shed_by_class=False):
        if int(max_batch_size) < 1:
            raise ValueError('max_batch_size must be >= 1')
        if scheduling not in ('edf', 'fifo'):
            raise ValueError("scheduling must be 'edf' or 'fifo', got %r"
                             % (scheduling, ))
        if priority_aging_s is not None and float(priority_aging_s) <= 0:
            raise ValueError('priority_aging_s must be > 0 (or None for '
                             'strict priority)')
        if priority_aging_s is not None and scheduling == 'fifo':
            # mirror ServingConfig's contradiction check: fifo never
            # sorts, so a silently-ignored aging window would read as
            # starvation relief that is not actually active
            raise ValueError("priority_aging_s only applies to 'edf' "
                             "scheduling — drop scheduling='fifo', or "
                             'drop the aging window')
        if shed_by_class and scheduling == 'fifo':
            # same contradiction shape: fifo never sheds at all
            raise ValueError("shed_by_class only applies to 'edf' "
                             "scheduling — drop scheduling='fifo', or "
                             'drop shed_by_class')
        self.max_batch_size = int(max_batch_size)
        self.max_wait_s = float(max_wait_s)
        self.scheduling = scheduling
        self.priority_aging_s = (float(priority_aging_s)
                                 if priority_aging_s is not None else None)
        self.shed_by_class = bool(shed_by_class)
        self._on_shed = on_shed
        self._service_estimate_fn = service_estimate_fn
        self._service_estimate_for = service_estimate_for
        self._pending = deque()
        self._cond = threading.Condition()
        self._closed = False

    def depth(self):
        with self._cond:
            return len(self._pending)

    def pending_rows(self):
        with self._cond:
            return sum(r.rows or 1 for r in self._pending)

    def oldest_age(self):
        """Age (seconds) of the oldest queued request; None when empty.
        The trace watchdog's queue-age stall probe reads this — a
        request aging far past max_wait means the worker is stuck."""
        with self._cond:
            if not self._pending:
                return None
            return time.time() - self._pending[0].enqueue_t

    def age_stats(self):
        """Queue-age stats (ISSUE 8): oldest/mean queued request age in
        seconds plus the depth — the registry's admission watermarks
        read these, and ``engine.metrics()`` surfaces them so a
        stalling queue is visible without waiting for the watchdog
        dump.  None when the queue is empty."""
        with self._cond:
            if not self._pending:
                return None
            now = time.time()
            ages = [now - r.enqueue_t for r in self._pending]
            return {'oldest_s': max(ages),
                    'mean_s': sum(ages) / len(ages),
                    'depth': len(ages)}

    def pending_trace_ids(self):
        """Trace ids of every queued request — the stall dump's view of
        work stuck BEFORE any dispatch record could enter the ring."""
        with self._cond:
            return [r.trace_id for r in self._pending]

    def submit(self, request):
        with self._cond:
            if self._closed:
                raise EngineClosedError('MicroBatcher is closed')
            self._pending.append(request)
            self._cond.notify_all()
        return request

    def close(self):
        """Stop accepting; wakes waiters so the worker can drain."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def _shed_locked(self):
        """Drop every pending request whose deadline has passed — or
        cannot be met within the engine's current service estimate —
        before any of them can head a lot (EDF mode only).  The shed
        callback errors each future with DeadlineExceededError; a shed
        must never take the worker down, so callback faults fall back
        to erroring the future directly."""
        if not self._pending:
            return
        now = time.time()
        if self.shed_by_class and (self._service_estimate_for is not None
                                   or self._service_estimate_fn
                                   is not None):
            # load-shedding by CLASS (ISSUE 12 satellite): walk the
            # queue in scheduling order (highest class first, EDF
            # within a class) ACCUMULATING service estimates — a
            # deadlined request sheds when the backlog scheduled ahead
            # of it already pushes its finish past its deadline.  Low
            # classes sort last, so under overload their deadlined work
            # sheds FIRST; within one class the walk order IS the EDF
            # order, so nothing reorders.  (Per-request estimates
            # accumulate without modeling lot coalescing — a
            # deliberate upper bound: admission errs toward shedding
            # work the backlog has already doomed.)
            def est_of(r):
                try:
                    if self._service_estimate_for is not None:
                        return float(self._service_estimate_for(r) or 0.0)
                    return float(self._service_estimate_fn() or 0.0)
                except Exception:
                    return 0.0

            maxp = max(r.priority for r in self._pending)
            order = sorted(
                self._pending,
                key=lambda r: _sched_key(r, now, self.priority_aging_s,
                                         maxp))
            doomed, cum = [], 0.0
            for r in order:
                e = est_of(r)
                if r.deadline_t is not None and r.deadline_t < now + cum + e:
                    doomed.append(r)
                    continue  # shed work frees its service slot
                cum += e
        elif self._service_estimate_for is not None:
            # per-signature horizon (ISSUE 9): each pending request is
            # judged against the estimate for ITS OWN signature; an
            # estimator fault degrades that request to the bare
            # past-deadline check, never to a worker death
            doomed = []
            for r in self._pending:
                if r.deadline_t is None:
                    continue
                try:
                    est = float(self._service_estimate_for(r) or 0.0)
                except Exception:
                    est = 0.0
                if r.deadline_t < now + est:
                    doomed.append(r)
        else:
            est = 0.0
            if self._service_estimate_fn is not None:
                try:
                    est = float(self._service_estimate_fn() or 0.0)
                except Exception:
                    est = 0.0
            horizon = now + est
            doomed = [r for r in self._pending
                      if r.deadline_t is not None
                      and r.deadline_t < horizon]
        if not doomed:
            return
        # one rebuild, not len(doomed) deque.remove scans: a stall can
        # doom most of an overloaded queue at once, and this runs with
        # the queue lock held
        doomed_ids = {id(r) for r in doomed}
        self._pending = deque(r for r in self._pending
                              if id(r) not in doomed_ids)
        for req in doomed:
            try:
                if self._on_shed is not None:
                    self._on_shed(req)
            except Exception:
                pass  # the fallback below still resolves the future
            if not req.done():
                req.set_error(DeadlineExceededError(
                    req.trace_id, req.deadline_ms,
                    round((now - req.deadline_t) * 1e3, 3)))

    def _select_locked(self):
        """The head request plus every signature-compatible follower
        that fits under max_batch_size; incompatible requests stay
        queued untouched.  Head choice and follower order are the
        scheduling policy: arrival order under 'fifo', priority-then-
        earliest-deadline under 'edf' (which is arrival order again
        when nothing carries a priority or deadline)."""
        if self.scheduling == 'edf' and len(self._pending) > 1 and \
                any(r.priority != 0 or r.deadline_t is not None
                    for r in self._pending):
            # only pay the sort when something actually carries an SLO:
            # for plain traffic _sched_key is a constant prefix plus
            # enqueue_t, i.e. exactly arrival order.  Aging promotes
            # only BELOW the highest pending real class, so a class
            # alone in the queue keeps pure EDF/arrival order.
            now = time.time()
            maxp = max(r.priority for r in self._pending)
            order = sorted(
                self._pending,
                key=lambda r: _sched_key(r, now, self.priority_aging_s,
                                         maxp))
        else:
            order = list(self._pending)
        head = order[0]
        lot, rows = [head], head.rows or 1
        if head.rows is None:
            return lot, rows  # unbatchable: its own lot
        for req in order[1:]:
            # same signature AND same kind: a forward request must
            # never ride a prefill lot (different program + fetches)
            if req.sig != head.sig or req.rows is None or \
                    req.kind != head.kind:
                continue
            if rows + req.rows > self.max_batch_size:
                break
            lot.append(req)
            rows += req.rows
        return lot, rows

    def next_lot(self, timeout=None, force=False):
        """Coalesce the next lot.  Blocks up to ``timeout`` (None =
        forever) for something flushable; returns [] on timeout, None
        when closed AND drained.  ``force`` flushes whatever is pending
        immediately, deadline notwithstanding (the inline/synchronous
        path and the stop-drain use it)."""
        deadline_out = None if timeout is None else time.time() + timeout
        with self._cond:
            while True:
                if self.scheduling == 'edf':
                    self._shed_locked()
                if self._pending:
                    lot, rows = self._select_locked()
                    # the deadline flush triggers on the OLDEST pending
                    # request (under EDF the lot head may be a newer,
                    # more urgent arrival — the latency bound must
                    # still cover the request left waiting)
                    flush_at = min(r.enqueue_t for r in self._pending) \
                        + self.max_wait_s
                    now = time.time()
                    # an unbatchable head (rows None: LoD/scalar feeds)
                    # can never coalesce — waiting out the deadline
                    # would be pure added latency
                    if force or self._closed or lot[0].rows is None or \
                            rows >= self.max_batch_size or now >= flush_at:
                        for req in lot:
                            self._pending.remove(req)
                        return lot
                    wait = flush_at - now
                elif self._closed:
                    return None
                elif force:
                    return []
                else:
                    wait = None
                if deadline_out is not None:
                    remaining = deadline_out - time.time()
                    if remaining <= 0:
                        return []
                    wait = remaining if wait is None else min(wait,
                                                              remaining)
                self._cond.wait(wait)
