"""Dynamic micro-batching queue: coalesce submitted requests into lots.

The reference serves inference through a per-request C-API call
(paddle_inference_api.h Run); a TPU amortizes its ~100ms tunnel
dispatch by batching.  The queue's contract:

  * a lot closes when its rows reach ``max_batch_size`` (full flush) OR
    the OLDEST waiting request has aged ``max_wait_s`` (deadline flush)
    — latency is bounded by max_wait even at low traffic;
  * only signature-compatible requests (same feed names, BUCKETED
    trailing dims and dtypes — the engine quantizes variable seq-len/
    resolution dims onto its TrailingDimBuckets ladder before the sig
    is taken, so mixed-length requests in one rung DO coalesce)
    coalesce; an incompatible request simply waits its turn as the
    head of a later lot — order is preserved per signature;
  * a lone request larger than max_batch_size forms its own lot (the
    bucket ladder gives it an exact entry) rather than being rejected.

Requests double as futures: ``submit`` returns an InferenceRequest the
caller blocks on with ``.result()``; the engine's worker thread fills
it after the trimmed fetches come back.
"""

import threading
import time
from collections import deque

__all__ = ['InferenceRequest', 'MicroBatcher']


class InferenceRequest(object):
    """One submitted feed dict + its future result.

    ``trailing`` maps a BUCKETED trailing extent back to this request's
    real extent ({padded_T: real_T}, axis 1) when the engine's
    trailing-dim ladder padded the request's seq/resolution dims up to
    a rung — the deliver path trims per-request fetches back to the
    real extents (engine._drain_one).

    ``trace`` is the request's TraceContext (fluid.trace): the engine
    threads ONE trace id from submit() through the micro-batch lot,
    dispatch, device sync and per-request trim, so a delivered request
    answers "where did my latency go" via ``breakdown()``.

    ``kind`` partitions the queue's lot space (ISSUE 7): 'forward'
    requests coalesce into eval lots, 'generate' ones
    (GenerationRequest) into PREFILL lots the engine routes to the
    decode lane — the two kinds never share a lot even if their feed
    signatures collide."""

    kind = 'forward'

    def __init__(self, feed, rows, sig, return_numpy=True, trailing=None,
                 trace=None):
        self.feed = feed
        self.rows = rows  # None for unbatchable (LoD / scalar) feeds
        self.sig = sig
        self.trailing = trailing or None
        self.return_numpy = return_numpy
        self.trace = trace
        self.enqueue_t = time.time()
        self.latency_s = None
        self._event = threading.Event()
        self._result = None
        self._error = None

    @property
    def trace_id(self):
        return self.trace.trace_id if self.trace is not None else None

    def breakdown(self):
        """The per-request stage breakdown (trace id, end-to-end ms,
        stage ms in pipeline order) — populated at delivery; None for a
        request created without a trace context."""
        return self.trace.breakdown() if self.trace is not None else None

    def done(self):
        return self._event.is_set()

    def set_result(self, result):
        self.latency_s = time.time() - self.enqueue_t
        self._result = result
        self._event.set()

    def set_error(self, exc):
        self.latency_s = time.time() - self.enqueue_t
        self._error = exc
        self._event.set()

    def result(self, timeout=None):
        """Block until delivered; re-raises the dispatch's exception."""
        if not self._event.wait(timeout):
            raise TimeoutError('inference request not completed within '
                               '%r s' % (timeout, ))
        if self._error is not None:
            raise self._error
        return self._result


class MicroBatcher(object):
    def __init__(self, max_batch_size=32, max_wait_s=0.005):
        if int(max_batch_size) < 1:
            raise ValueError('max_batch_size must be >= 1')
        self.max_batch_size = int(max_batch_size)
        self.max_wait_s = float(max_wait_s)
        self._pending = deque()
        self._cond = threading.Condition()
        self._closed = False

    def depth(self):
        with self._cond:
            return len(self._pending)

    def pending_rows(self):
        with self._cond:
            return sum(r.rows or 1 for r in self._pending)

    def oldest_age(self):
        """Age (seconds) of the oldest queued request; None when empty.
        The trace watchdog's queue-age stall probe reads this — a
        request aging far past max_wait means the worker is stuck."""
        with self._cond:
            if not self._pending:
                return None
            return time.time() - self._pending[0].enqueue_t

    def pending_trace_ids(self):
        """Trace ids of every queued request — the stall dump's view of
        work stuck BEFORE any dispatch record could enter the ring."""
        with self._cond:
            return [r.trace_id for r in self._pending]

    def submit(self, request):
        with self._cond:
            if self._closed:
                raise RuntimeError('MicroBatcher is closed')
            self._pending.append(request)
            self._cond.notify_all()
        return request

    def close(self):
        """Stop accepting; wakes waiters so the worker can drain."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def _select_locked(self):
        """The head request plus every signature-compatible follower
        that fits under max_batch_size (order preserved; incompatible
        requests stay queued untouched)."""
        head = self._pending[0]
        lot, rows = [head], head.rows or 1
        if head.rows is None:
            return lot, rows  # unbatchable: its own lot
        for req in list(self._pending)[1:]:
            # same signature AND same kind: a forward request must
            # never ride a prefill lot (different program + fetches)
            if req.sig != head.sig or req.rows is None or \
                    req.kind != head.kind:
                continue
            if rows + req.rows > self.max_batch_size:
                break
            lot.append(req)
            rows += req.rows
        return lot, rows

    def next_lot(self, timeout=None, force=False):
        """Coalesce the next lot.  Blocks up to ``timeout`` (None =
        forever) for something flushable; returns [] on timeout, None
        when closed AND drained.  ``force`` flushes whatever is pending
        immediately, deadline notwithstanding (the inline/synchronous
        path and the stop-drain use it)."""
        deadline_out = None if timeout is None else time.time() + timeout
        with self._cond:
            while True:
                if self._pending:
                    lot, rows = self._select_locked()
                    flush_at = lot[0].enqueue_t + self.max_wait_s
                    now = time.time()
                    # an unbatchable head (rows None: LoD/scalar feeds)
                    # can never coalesce — waiting out the deadline
                    # would be pure added latency
                    if force or self._closed or lot[0].rows is None or \
                            rows >= self.max_batch_size or now >= flush_at:
                        for req in lot:
                            self._pending.remove(req)
                        return lot
                    wait = flush_at - now
                elif self._closed:
                    return None
                elif force:
                    return []
                else:
                    wait = None
                if deadline_out is not None:
                    remaining = deadline_out - time.time()
                    if remaining <= 0:
                        return []
                    wait = remaining if wait is None else min(wait,
                                                              remaining)
                self._cond.wait(wait)
