"""Multi-model serving: N named InferenceEngines over ONE shared
device/mesh, with cross-model HBM arbitration.

The single-model engine (engine.py) already amortizes the TPU tunnel;
what a production server needs on top is the FLEET view the reference
stack never had (one predictor per process): which models are loaded,
what each one pins in device memory, and who gets evicted when the next
model arrives.  ``ModelRegistry`` is that subsystem:

  * **lifecycle** — ``load(name, dirname)`` (a save_inference_model
    dir) or ``load(name, program=...)`` builds a per-model engine with
    its own scope + executor over the registry's shared place/mesh;
    ``unload`` stops and forgets it; ``warm`` pre-compiles the bucket
    ladder; ``status()`` snapshots the fleet.  All thread-safe against
    in-flight requests.
  * **HBM arbiter** (arbiter.py) — every model's weight + executable
    footprint is accounted (seeded from
    ``fluid.contrib.memory_usage_calc``, corrected by live jax buffer
    stats once it serves), admission-controlled against
    ``hbm_budget_bytes``, and LRU-evicted to HOST memory when the
    budget forces it: the victim engine is paused (in-flight dispatches
    drain), its scope buffers demote to host ndarrays bitwise, and its
    executables drop — the next request to it transparently re-stages
    and recompiles (counted as a reload).
  * **router** — ``submit(model, feed)`` ensures residency, bumps the
    LRU, tracks per-model request/row rates, and forwards to the
    model's engine queue; each engine's worker drains its own queue
    while a shared dispatch GATE keeps device dispatches fair across
    models (one bounded critical section per dispatch — no model can
    hog the chip between another's dispatches).  The budget binds at
    ROUTING time: a request already queued on an engine when its model
    is evicted simply re-stages at its own dispatch (correct, slower),
    and the account is corrected at the model's next routing.
  * **observability** — per-model engine snapshots ride the profiler
    sidecar under the registry's metrics source; spans land in
    per-model ``:serving/<model>`` timeline rows (tools/timeline.py);
    ``metrics()`` carries the arbiter's eviction/reload/admission
    counters next to the router's rates.

    reg = serving.ModelRegistry(hbm_budget_bytes=2 << 30)
    reg.load('ranker', '/models/ranker')
    reg.load('retriever', '/models/retriever')
    with reg:                                  # starts every worker
        out, = reg.infer('ranker', {'x': batch})
    print(reg.status(), reg.metrics())
"""

import itertools
import json
import os
import threading
import time
import weakref

import numpy as np

from ..fluid import core
from ..fluid import profiler as _profiler
from ..fluid import trace as _trace
from ..fluid.flags import FLAGS as _FLAGS
from .arbiter import HBMArbiter, HBMBudgetError, program_seed_bytes
from .engine import InferenceEngine, ServingConfig
from .errors import OverloadedError

__all__ = ['ModelRegistry', 'WARM_CATALOG_BASENAME']

# the fleet's compile catalog (ISSUE 8): every registry.warm() call is
# recorded here as a replayable signature set (batch rungs x trailing
# rungs x decode-prefill extents), persisted NEXT TO the persistent XLA
# compile cache (FLAGS_xla_compile_cache_dir) — the pairing is the
# point: the XLA cache holds the compiled executables keyed by traced
# signature, and the catalog holds WHICH signatures a fresh process
# must re-trace to hit them.  registry.prewarm(catalog) replays it so a
# restarted server compiles nothing on first traffic.
WARM_CATALOG_BASENAME = 'serving_warm_catalog.json'

# the decode-state cache's arbiter account rides next to its model's
# weight account under this suffix (ISSUE 7): `<model>:decode-cache` —
# evictable on its own (an idle generation model's slabs free without
# demoting its weights) and typed-rejected at load when the cache alone
# can never fit the budget
DECODE_CACHE_SUFFIX = ':decode-cache'

# a MESH-ROW-SHARDED embedding table's arbiter account rides next to
# its model's weight account under this suffix (ISSUE 11):
# `<model>:embed-table:<var>` — charged at the table's PER-DEVICE shard
# bytes (the budget is one chip's HBM; GSPMD lays only 1/extent of the
# rows on each device), so a table bigger than a single device's budget
# is admitted SHARDED while the same table unsharded stays inside the
# model's own full-size seed and draws the typed HBMBudgetError at load
EMBED_TABLE_SUFFIX = ':embed-table'

# a TWO-TIER cached table's account (ISSUE 12) bills the ``[C, D]`` HBM
# hot-row slab set (weight + optimizer accumulators), NOT the [V, D]
# master — that stays host-resident in the cache's AsyncSparseEmbedding
# tier.  `<model>:embed-cache:<var>` — a table bigger than the WHOLE
# mesh budget therefore ADMITS with overflow='host' semantics, while the
# identical program served without the cache keeps the full table in its
# model seed and draws the typed HBMBudgetError (the PR 10 behavior,
# now the pinned counterfactual).
EMBED_CACHE_SUFFIX = ':embed-cache'


def _row_sharded_tables(engine):
    """``{var_name: (global_bytes, per_device_bytes)}`` for every
    persistable >=2-D var of the engine's program whose sharding
    annotation row-shards it over a REAL mesh axis of the engine's own
    mesh.  Empty for single-device engines: an unsharded table lives
    whole on the one chip and stays inside the model's seed/footprint
    account."""
    pe = engine._pe
    if pe is None:
        return {}
    from ..parallel.api import sharding_of
    mesh_axes = dict(zip(pe._mesh.axis_names, pe._mesh.devices.shape))
    out = {}
    for var in engine._program.global_block().vars.values():
        if not getattr(var, 'persistable', False):
            continue
        shape = tuple(var.shape or ())
        if len(shape) < 2 or any(d is None or int(d) <= 0 for d in shape):
            continue
        spec = sharding_of(var)
        if spec is None or not len(spec) or spec[0] is None:
            continue
        axes = spec[0] if isinstance(spec[0], tuple) else (spec[0], )
        factor = 1
        for ax in axes:
            factor *= int(mesh_axes.get(ax, 1))
        if factor <= 1:
            continue
        itemsize = np.dtype(var.np_dtype).itemsize
        gbytes = int(np.prod([int(d) for d in shape])) * int(itemsize)
        out[var.name] = (gbytes, -(-gbytes // factor))
    return out


class _ModelEntry(object):
    __slots__ = ('name', 'engine', 'dirname', 'loaded_t', 'requests',
                 'rows', 'first_req_t', 'last_req_t', 'overload_rejects',
                 'table_accounts', 'embed_cache_accounts')

    def __init__(self, name, engine, dirname):
        self.name = name
        self.engine = engine
        self.dirname = dirname
        self.loaded_t = time.time()
        self.requests = 0
        self.rows = 0
        self.first_req_t = None
        self.last_req_t = None
        self.overload_rejects = 0
        # {account_name: table var name} for mesh-row-sharded embedding
        # tables (ISSUE 11) — per-device-charged sibling accounts
        self.table_accounts = {}
        # {account_name: table var name} for two-tier cached tables
        # (ISSUE 12) — slab-bytes-charged sibling accounts
        self.embed_cache_accounts = {}


class ModelRegistry(object):
    """Host N named models behind one router + HBM arbiter (module
    docstring has the design)."""

    def __init__(self, hbm_budget_bytes=None, place=None, parallel=False,
                 mesh=None, config=None, name=None):
        self.place = place if place is not None else (
            core.TPUPlace() if core.is_compiled_with_tpu()
            else core.CPUPlace())
        self.parallel = bool(parallel) or mesh is not None
        self.mesh = mesh
        self.config = config  # default ServingConfig for loaded models
        self.name = name or 'model-registry'
        self.arbiter = HBMArbiter(hbm_budget_bytes)
        self._models = {}
        # the compile catalog (ISSUE 8): replayable records of every
        # warm() this registry served, persisted next to the XLA cache
        self._warm_catalog = []
        # ONE reentrant lock over the model table + arbiter decisions:
        # a submit ensuring residency (which may pause + evict another
        # model) must never interleave with a load/unload mutating the
        # table.  Engine queues drain on their own workers, so holding
        # this across an eviction stalls ROUTING, not in-flight serving.
        self._lock = threading.RLock()
        # the fair-dispatch turnstile shared by every hosted engine
        self._dispatch_gate = threading.Lock()
        self._started = False
        self._closed = False
        ref = weakref.ref(self)
        self._metrics_fn = lambda: (ref().metrics() if ref() else None)
        self._metrics_key = _profiler.register_metrics_source(
            self.name, self._metrics_fn)
        weakref.finalize(self, _profiler.unregister_metrics_source,
                         self._metrics_key, self._metrics_fn)

    # ---- lifecycle -----------------------------------------------------

    def load(self, name, dirname=None, program=None, feed_names=None,
             fetch_list=None, scope=None, executor=None, config=None,
             model_filename=None, params_filename=None, generation=None,
             embed_caches=None):
        """Load a model under ``name``: either a save_inference_model
        ``dirname`` (own scope + executor, the production form) or an
        explicit ``program`` (+ fetch_list, and a scope holding its
        params).  Admission-checked against the HBM budget BEFORE any
        device work: a model that can never fit raises HBMBudgetError
        with nothing loaded."""
        if not name or '/' in str(name) or ':' in str(name):
            raise ValueError(
                'model name must be a non-empty string without "/" or '
                '":" (it keys metrics sources, timeline rows, and the '
                'arbiter account namespace — ":decode-cache" / '
                '":embed-table:" suffixes route eviction), got %r'
                % (name, ))
        with self._lock:
            if self._closed:
                raise RuntimeError('registry is closed')
            if name in self._models:
                raise ValueError(
                    'model %r is already loaded — unload() it first '
                    '(in-place replacement would strand its queued '
                    'requests)' % name)
            cfg = config or self.config or ServingConfig()
            if dirname is not None:
                if generation is not None:
                    # checked BEFORE the engine exists: a post-
                    # construction raise here would leak its profiler
                    # registration + param scope (the cleanup except
                    # below only guards the admission path)
                    raise ValueError(
                        'load(%r): generation= requires program= (the '
                        'prefill/step programs reference live '
                        'Variables, which a saved-model dir cannot '
                        'carry)' % name)
                if embed_caches:
                    raise ValueError(
                        'load(%r): embed_caches= requires program= '
                        '(the cache is bound to a live scope holding '
                        'the slab vars)' % name)
                engine = InferenceEngine.from_saved_model(
                    dirname, place=self.place,
                    model_filename=model_filename,
                    params_filename=params_filename,
                    parallel=self.parallel, mesh=self.mesh,
                    config=cfg, name=name)
            elif program is not None:
                if fetch_list is None:
                    raise ValueError('load(program=...): fetch_list is '
                                     'required')
                engine = InferenceEngine(
                    program, feed_names=feed_names, fetch_list=fetch_list,
                    place=self.place, scope=scope, executor=executor,
                    parallel=self.parallel, mesh=self.mesh,
                    config=cfg, name=name, generation=generation,
                    embed_caches=embed_caches)
            else:
                raise ValueError('load(): pass dirname= or program=')
            cache_account = name + DECODE_CACHE_SUFFIX
            tables = _row_sharded_tables(engine)
            table_accounts = {
                '%s%s:%s' % (name, EMBED_TABLE_SUFFIX, var): var
                for var in tables
            }
            embed_cache_accounts = {
                '%s%s:%s' % (name, EMBED_CACHE_SUFFIX, c.var): c.var
                for c in engine._embed_caches
            }
            try:
                for var in tables:
                    # a pre-staged table (startup ran on the DEFAULT
                    # device, or a trainer's scope is being served
                    # directly) sits in the scope as one whole-table
                    # device array — the first routing correction would
                    # bill the model account its full GLOBAL bytes and
                    # reject a budget sized for the sharded layout.
                    # Demote it once; the first sharded dispatch lays
                    # it out over the mesh bitwise.
                    engine.evict_table_to_host(var)
                # admission gate: seed the account from the program's
                # var-sum estimate at the TOP bucket size (weights +
                # the largest lot's activations the executables pin)
                seed = program_seed_bytes(engine._program,
                                          max(engine.buckets.sizes))
                if tables:
                    # mesh-row-sharded tables (ISSUE 11) move out of
                    # the model's full-size seed into their own
                    # PER-DEVICE-charged accounts: only 1/extent of the
                    # rows lands on any one chip, so a table bigger
                    # than the whole budget still admits sharded —
                    # while the same table unsharded stays in the seed
                    # and draws the typed reject below
                    seed = max(
                        seed - sum(g for g, _ in tables.values()), 1024)
                if engine._embed_caches:
                    # TWO-TIER cached tables (ISSUE 12): the [V, D]
                    # master never goes on device — it moves out of the
                    # seed entirely, and the slab-sized account below
                    # is what the budget arbitrates.  A table past the
                    # WHOLE mesh budget therefore admits with the host
                    # overflow tier; the identical non-overflow program
                    # keeps it in the seed and draws the typed reject.
                    seed = max(
                        seed - sum(c.master_nbytes()
                                   for c in engine._embed_caches), 1024)
                self.arbiter.admit(name, seed)
                for acct, var in table_accounts.items():
                    self.arbiter.admit(acct, tables[var][1])
                for acct, var in embed_cache_accounts.items():
                    self.arbiter.admit(
                        acct, engine.embed_cache_of(var).slab_nbytes())
                if engine._decode_cache is not None:
                    # the decode-state cache is a FIRST-CLASS account:
                    # its slab bytes are exact (static slot shapes), and
                    # a cache that alone exceeds the budget is a typed
                    # reject at load, not an OOM mid-generation
                    self.arbiter.admit(
                        cache_account,
                        engine.generation.cache_nbytes(
                            engine._decode_cache.slots))
                entry = _ModelEntry(name, engine, dirname)
                entry.table_accounts = table_accounts
                entry.embed_cache_accounts = embed_cache_accounts
                self._models[name] = entry
                # make room NOW (evicting LRU peers), so the first
                # request pays staging, not arbitration
                self.arbiter.ensure(name, self._evict_to_host)
                for acct in table_accounts:
                    self.arbiter.ensure(acct, self._evict_to_host)
                for acct in embed_cache_accounts:
                    self.arbiter.ensure(acct, self._evict_to_host)
                if engine._decode_cache is not None:
                    self.arbiter.ensure(cache_account,
                                        self._evict_to_host)
            except Exception:
                # ANY failure (budget reject, an estimator choking on
                # an exotic var, ...) must not leak the constructed
                # engine — its profiler registration and param scope
                # would outlive the failed load
                self.arbiter.drop(name)
                self.arbiter.drop(cache_account)
                for acct in table_accounts:
                    self.arbiter.drop(acct)
                for acct in embed_cache_accounts:
                    self.arbiter.drop(acct)
                self._models.pop(name, None)
                engine.stop()
                raise
            engine._gate = self._dispatch_gate
            if self._started:
                engine.start()
            return engine

    def unload(self, name):
        """Stop the model's engine (drains its queue + in-flight
        dispatches), drop its account, and forget it."""
        with self._lock:
            entry = self._models.pop(name, None)
            if entry is None:
                raise KeyError('model %r is not loaded' % name)
            self.arbiter.drop(name)
            self.arbiter.drop(name + DECODE_CACHE_SUFFIX)
            for acct in entry.table_accounts:
                self.arbiter.drop(acct)
            for acct in entry.embed_cache_accounts:
                self.arbiter.drop(acct)
        entry.engine.stop()

    def warm(self, name, bucket_ladder=None, trailing=None,
             decode_prefill=None):
        """Pre-compile the model's executables across its bucket ladder
        (or an explicit one) with zero-filled requests, so first real
        traffic pays staging, not XLA compiles.  Returns the number of
        warm requests served.

        ``trailing`` extends the warm set along the TRAILING dims
        (ISSUE 5): ``{feed_name: [extents]}`` warms one request per
        (batch rung x trailing extent) for that feed — an LoD-declared
        feed warms as a zero-filled LoD batch of that uniform length
        (so the prepared signature, padded data + @SEQLEN, matches
        real traffic whose lengths bucket to the same rung), a dense
        feed substitutes the extent into axis 1.  Several trailing
        feeds warm the FULL cross-product of their rungs — trailing
        extents correlate in real traffic (both sides of a translation
        pair bucket long together), so the correlated multi-feed
        signatures are exactly the ones that must not stay cold; the
        warm set is len(ladder) x prod(len(extents)), which the caller
        bounds through the extents passed.

        ``decode_prefill`` warms the GENERATION lane (ISSUE 7): one
        zero-filled single-sequence prompt per extent runs through
        ``submit_generate`` with ``max_len=1`` — compiling the prefill
        executable at each prompt-length rung plus the decode-step
        scan executable, so first real generation traffic pays
        staging, not XLA compiles.  A decode-only call (no
        bucket_ladder/trailing) skips the forward-surface warm.

        Every successful warm is RECORDED into the registry's compile
        catalog (ISSUE 8) and — when FLAGS_xla_compile_cache_dir is set
        — persisted as ``serving_warm_catalog.json`` next to the XLA
        cache, so ``prewarm()`` on a fresh process can replay the
        exact signature set this fleet compiled."""
        entry = self._entry(name)
        engine = entry.engine
        served = 0
        # materialize iterator-valued args ONCE, before anything reads
        # them: the catalog record and the warm body must see the same
        # extents (an iterator drained by the record would warm nothing
        # while recording rungs)
        if decode_prefill is not None:
            decode_prefill = [int(e) for e in decode_prefill]
        trailing = {str(f): [int(e) for e in v]
                    for f, v in (trailing or {}).items()} or None
        record = {
            'model': str(name),
            'bucket_ladder': ([int(b) for b in bucket_ladder]
                              if bucket_ladder is not None else None),
            'trailing': trailing,
            'decode_prefill': decode_prefill,
        }
        if decode_prefill is not None:
            spec = engine.generation
            if spec is None:
                raise ValueError(
                    'warm(%r): decode_prefill= but the model serves no '
                    'generation lane — load it with generation='
                    % name)
            extents = list(decode_prefill)
            if not extents:
                raise ValueError(
                    'warm(%r): decode_prefill is empty — pass at least '
                    'one prompt-length extent' % name)
            pblock = spec.prefill_program.global_block()
            for extent in dict.fromkeys(int(e) for e in extents):
                feed = {}
                for fname in spec.prefill_feeds:
                    var = pblock.vars[fname]
                    if not getattr(var, 'lod_level', 0):
                        raise ValueError(
                            'warm(%r): prefill feed %r is not a '
                            'sequence (lod_level=0) — decode_prefill '
                            'warms prompt-length rungs; warm dense '
                            'prompts with real traffic'
                            % (name, fname))
                    from ..fluid.lod_tensor import create_lod_tensor
                    shape = [int(d) for d in var.shape[1:]]
                    if any(d < 0 for d in shape):
                        raise ValueError(
                            'warm(%r): prefill feed %r has a non-batch '
                            'dynamic dim %s — warm it with real '
                            'traffic instead' % (name, fname, var.shape))
                    rows = np.zeros((extent, ) + tuple(shape),
                                    var.np_dtype).tolist()
                    feed[fname] = create_lod_tensor([rows], [[extent]])
                self.generate(name, feed, max_len=1, timeout=600)
                served += 1
            if bucket_ladder is None and not trailing:
                self._record_warm(record)
                return served
        ladder = list(bucket_ladder if bucket_ladder is not None
                      else engine.buckets.sizes)
        trailing = trailing or {}
        feed_names = engine._feed_names
        if not feed_names:
            raise ValueError(
                'warm(%r): the engine has no feed_names — load the '
                'model from a save_inference_model dir, or pass '
                'feed_names= at load()' % name)
        unknown = sorted(set(trailing) - set(feed_names))
        if unknown:
            # a typo'd key would silently warm NOTHING useful while
            # reporting served rungs
            raise ValueError(
                'warm(%r): trailing names %s are not feeds of this '
                'model (feeds: %s)' % (name, unknown, sorted(feed_names)))
        empty = sorted(f for f, extents in trailing.items()
                       if not list(extents))
        if empty:
            # an empty extent list would die later on trailing[f][0]
            # with a raw IndexError
            raise ValueError(
                'warm(%r): trailing extents for %s are empty — pass '
                'at least one extent per feed' % (name, empty))
        block = engine._program.global_block()

        def zero_feed(fname, rows, extent):
            var = block.vars[fname]
            shape = [int(d) for d in var.shape]
            shape[0] = int(rows)
            if getattr(var, 'lod_level', 0):
                if extent is None:
                    raise ValueError(
                        'warm(%r): feed %r is a sequence (lod_level=%d) '
                        '— pass trailing={%r: [extents]} to warm its '
                        'seq-len rungs' % (name, fname, var.lod_level,
                                           fname))
                if any(d < 0 for d in shape[1:]):
                    # the extent fills the TIME axis, not these: a seq
                    # feed with another dynamic dim would otherwise die
                    # inside np.zeros with a raw 'negative dimensions'
                    # error instead of this message
                    raise ValueError(
                        'warm(%r): feed %r has a non-batch dynamic dim '
                        '%s — warm it with real traffic instead'
                        % (name, fname, var.shape))
                from ..fluid.lod_tensor import create_lod_tensor
                t = int(extent)
                rows_data = [np.zeros((t, ) + tuple(shape[1:]),
                                      var.np_dtype).tolist()
                             for _ in range(int(rows))]
                return create_lod_tensor(rows_data, [[t] * int(rows)])
            if extent is not None:
                if len(shape) < 2:
                    # silently dropping the extent would warm duplicate
                    # all-zero signatures while reporting them as served
                    # rungs — the same 'warmed nothing while reporting
                    # rungs' failure the unknown-name check catches
                    raise ValueError(
                        'warm(%r): feed %r has no trailing axis '
                        '(shape %s) — drop it from trailing='
                        % (name, fname, var.shape))
                axes = set(engine.trailing.ladder_axes(fname)) \
                    if engine.trailing is not None else set()
                if axes and axes != {1}:
                    # flat extents substitute axis 1; a dict-form
                    # ladder on other axes would warm signatures real
                    # traffic never produces while reporting served
                    # rungs
                    raise ValueError(
                        'warm(%r): feed %r buckets on axes %s — flat '
                        'trailing extents warm axis 1 only; warm those '
                        'rungs with real traffic'
                        % (name, fname, sorted(axes)))
                if int(var.shape[1]) >= 0:
                    raise ValueError(
                        'warm(%r): feed %r has a STATIC axis-1 extent '
                        '%d — there are no axis-1 rungs to warm; drop '
                        'it from trailing='
                        % (name, fname, int(var.shape[1])))
                shape[1] = int(extent)
            if any(d < 0 for d in shape[1:]):
                raise ValueError(
                    'warm(%r): feed %r has a non-batch dynamic dim '
                    '%s — warm it with real traffic instead'
                    % (name, fname, var.shape))
            return np.zeros(shape, dtype=var.np_dtype)

        # the FULL cross-product of per-feed rungs: trailing extents
        # correlate in real traffic, so varying one feed while pinning
        # the others at their first extent would leave exactly the
        # dominant multi-feed signatures cold
        t_names = sorted(trailing)
        combos = list(itertools.product(
            *(list(dict.fromkeys(trailing[f])) for f in t_names)))
        for rows in ladder:
            for combo in combos or [()]:
                extents = dict(zip(t_names, combo))
                feed = {fname: zero_feed(fname, rows,
                                         extents.get(fname))
                        for fname in feed_names}
                self.infer(name, feed, timeout=600)
                served += 1
        self._record_warm(record)
        return served

    # ---- prewarm catalog (ISSUE 8) -------------------------------------

    def warm_catalog_path(self):
        """Where the compile catalog persists: next to the persistent
        XLA compile cache (FLAGS_xla_compile_cache_dir), or None when
        no cache dir is configured (the catalog then lives in-memory
        only — ``warm_catalog()`` still returns it)."""
        cache_dir = _FLAGS.xla_compile_cache_dir
        if not cache_dir:
            return None
        return os.path.join(cache_dir, WARM_CATALOG_BASENAME)

    def warm_catalog(self):
        """The recorded warm set: one replayable dict per distinct
        warm() call (model, bucket_ladder, trailing, decode_prefill)."""
        with self._lock:
            return [dict(r) for r in self._warm_catalog]

    def _record_warm(self, record):
        """Append one warm record (deduped — prewarm replays through
        warm(), which must not grow the catalog it is replaying) and
        persist the catalog atomically next to the XLA cache.  The
        write MERGES with what is already on disk: a staged restart
        that loaded (and re-warmed) only some models — or a peer
        process sharing the cache dir — has records there for models
        THIS registry never warmed, and overwriting would delete their
        replay set."""
        path = self.warm_catalog_path()
        # the read-merge-replace stays under self._lock: two threads
        # warming concurrently would otherwise race read-vs-replace and
        # one record would vanish from disk (a lost update).  Peer
        # PROCESSES sharing the cache dir can still interleave — the
        # merge shrinks that window but does not close it; same-process
        # durability is the contract the prewarm acceptance pins.
        with self._lock:
            if record not in self._warm_catalog:
                self._warm_catalog.append(record)
            if path is None:
                return
            catalog = [dict(r) for r in self._warm_catalog]
            tmp = path + '.tmp'
            try:
                try:
                    with open(path) as f:
                        on_disk = json.load(f)
                except (OSError, ValueError):
                    on_disk = []
                merged = list(on_disk) + [r for r in catalog
                                          if r not in on_disk]
                with open(tmp, 'w') as f:
                    json.dump(merged, f, indent=1)
                    f.write('\n')
                os.replace(tmp, path)
            except OSError:
                # an unwritable cache dir must not fail the warm
                # itself — the in-memory catalog still serves
                # same-process prewarms
                try:
                    os.remove(tmp)
                except OSError:
                    pass

    def prewarm(self, catalog=None):
        """Replay a compile catalog on THIS registry (the fleet
        cold-start path, ISSUE 8): for every record whose model is
        loaded, re-run ``warm()`` with the recorded bucket ladder x
        trailing rungs x decode-prefill extents.  With
        FLAGS_xla_compile_cache_dir pointing at the SAME persistent
        cache the recording process used, each replayed compile is a
        disk hit, and first real traffic at the recorded signatures
        compiles nothing (``compile_count`` delta 0 — the acceptance
        bar).

        ``catalog``: a path to a catalog JSON, an already-loaded list
        of records, or None to read the default
        ``warm_catalog_path()``.  Records for models not currently
        loaded are skipped (reported, not raised — a fleet restart may
        stage models in stages).  Returns
        {'served', 'replayed', 'skipped_models'}."""
        if catalog is None:
            catalog = self.warm_catalog_path()
            if catalog is None:
                raise ValueError(
                    'prewarm(): no catalog given and no '
                    'FLAGS_xla_compile_cache_dir to read the default '
                    'from — pass a path or a record list')
        if isinstance(catalog, str):
            with open(catalog) as f:
                catalog = json.load(f)
        served = replayed = 0
        skipped = []
        for rec in list(catalog):
            model = rec.get('model')
            with self._lock:
                loaded = model in self._models
            if not loaded:
                skipped.append(model)
                continue
            served += self.warm(
                model, bucket_ladder=rec.get('bucket_ladder'),
                trailing=rec.get('trailing'),
                decode_prefill=rec.get('decode_prefill'))
            replayed += 1
        return {'served': served, 'replayed': replayed,
                'skipped_models': sorted(set(skipped))}

    def _entry(self, name):
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise KeyError(
                    'model %r is not loaded (loaded: %s)'
                    % (name, sorted(self._models)))
            return entry

    def models(self):
        with self._lock:
            return sorted(self._models)

    # ---- arbiter plumbing ----------------------------------------------

    def _evict_to_host(self, victim):
        """The arbiter's evict callback: pause the victim engine (its
        in-flight dispatches drain), demote its device buffers to host
        ndarrays bitwise, drop its executables.  Returns the live bytes
        moved (the arbiter's account correction).  A ``:decode-cache``
        victim demotes its model's decode slabs instead of the weights
        — an idle generation model's cache frees on its own."""
        if victim.endswith(DECODE_CACHE_SUFFIX):
            owner = victim[:-len(DECODE_CACHE_SUFFIX)]
            return self._models[owner].engine.evict_decode_cache()
        if EMBED_TABLE_SUFFIX + ':' in victim:
            # a sharded embedding table demotes on its OWN (ISSUE 11):
            # the var's mesh shards copy back to one host ndarray under
            # the owner's paused window; the moved bytes are the
            # PER-DEVICE share — the unit its account is charged in
            owner, _, var = victim.partition(EMBED_TABLE_SUFFIX + ':')
            return self._models[owner].engine.evict_table_to_host(var)
        if EMBED_CACHE_SUFFIX + ':' in victim:
            # a two-tier cache's slabs demote on their OWN (ISSUE 12):
            # paused-window flush (dirty rows back to the host master,
            # any staged exchange applied first) + bitwise slab
            # demotion; the next dispatch re-stages transparently
            owner, _, var = victim.partition(EMBED_CACHE_SUFFIX + ':')
            return self._models[owner].engine.evict_embed_cache_to_host(
                var)
        entry = self._models[victim]
        moved, _ = entry.engine.evict_to_host()
        return moved

    def audit(self):
        """Run the arbiter's ``jax.live_arrays()`` cross-check now and
        return it (also kept on the arbiter and surfaced as the
        ``audit`` block of ``metrics()``): accounted-resident bytes vs
        what the runtime actually holds live, drift included."""
        return self.arbiter.audit()

    def _ensure_resident(self, name, decode=False):
        """Dispatch-time gate: budget-arbitrate ``name`` resident (LRU
        peers evict as needed) and correct resident accounts to live
        buffer stats.  ``decode=True`` (a routed generation request)
        additionally ensures the model's decode-cache account — its
        slabs re-stage transparently at the next decode dispatch after
        an eviction."""
        with self._lock:
            entry = self._entry(name)
            if entry.table_accounts or entry.embed_cache_accounts:
                # sharded-table engines bill the model account at the
                # shard-aware PER-DEVICE footprint (the budget is one
                # chip's HBM — a trainer scope's co-sharded moments
                # must not bill global bytes), with each table's own
                # per-device share moved onto its account below
                footprint = entry.engine.hbm_footprint()
            else:
                footprint = entry.engine.device_footprint()
            for acct, var in entry.table_accounts.items():
                _, per_dev = entry.engine.table_live_bytes(var)
                footprint = max(footprint - per_dev, 0)
                self.arbiter.correct(acct, per_dev)
            for acct, var in entry.embed_cache_accounts.items():
                live = entry.engine.embed_cache_live_bytes(var)
                footprint = max(footprint - live, 0)
                self.arbiter.correct(acct, live)
            self.arbiter.correct(name, footprint)
            self.arbiter.ensure(name, self._evict_to_host)
            for acct in entry.table_accounts:
                self.arbiter.ensure(acct, self._evict_to_host)
            for acct in entry.embed_cache_accounts:
                self.arbiter.ensure(acct, self._evict_to_host)
            if decode:
                cache = name + DECODE_CACHE_SUFFIX
                self.arbiter.correct(
                    cache, entry.engine._decode_cache.nbytes())
                self.arbiter.ensure(cache, self._evict_to_host)
            return entry

    # ---- router --------------------------------------------------------

    def _check_admission(self, model):
        """Per-model overload admission (ISSUE 8): when the model's
        ServingConfig carries queue watermarks (admit_queue_depth /
        admit_queue_age_ms) and its engine's queue has crossed one,
        refuse the request at the DOOR with a typed OverloadedError —
        BEFORE paying arbitration (an eviction on behalf of a request
        that would only queue toward deadline death helps nobody).  The
        retry-after hint is one queue-drain window: the oldest queued
        age (how far behind the worker is) floored at the batching
        wait.  (The entry lookup is NOT returned: _ensure_resident must
        re-resolve it under the lock anyway, or it would race an
        unload between the two calls.)"""
        entry = self._entry(model)
        cfg = entry.engine.config
        depth_wm = cfg.admit_queue_depth
        age_wm = cfg.admit_queue_age_s
        if depth_wm is None and age_wm is None:
            return
        depth = entry.engine._batcher.depth()
        age = entry.engine._batcher.oldest_age() or 0.0
        if cfg.adaptive_admission and (
                (depth_wm is not None and depth >= 0.5 * depth_wm) or
                (age_wm is not None and age >= 0.5 * age_wm)):
            # adaptive watermarks (ISSUE 9): scale the static marks by
            # the measured drain/arrival ratio, clamped to [0.5, 2.0].
            # An engine whose drain keeps up (ratio >= 1) tolerates a
            # deeper queue — the static watermark was sized for a
            # falling-behind worst case, and rejecting an absorbable
            # burst wastes goodput; one falling behind (ratio < 1)
            # admits at a proportionally SHALLOWER depth, shedding at
            # the door while the queue can still drain what it holds.
            # Before both rates are measurable the static marks stand.
            # Gated on the queue being at least HALFWAY to a static
            # mark: below that no clamped scale can change the
            # verdict, so the hot submit path skips the two
            # lock-guarded rate() passes entirely.
            rates = entry.engine.rate_stats()
            arrival, drain = rates['arrival_req_s'], rates['drain_req_s']
            if arrival and drain:
                scale = min(max(drain / arrival, 0.5), 2.0)
                if depth_wm is not None:
                    depth_wm = max(depth_wm * scale, 1.0)
                if age_wm is not None:
                    age_wm = age_wm * scale
        if (depth_wm is not None and depth >= depth_wm) or \
                (age_wm is not None and age >= age_wm):
            with self._lock:
                entry.overload_rejects += 1
            raise OverloadedError(
                model, depth, age,
                retry_after_s=round(max(age, cfg.max_wait_s), 4))

    def submit(self, model, feed, return_numpy=True, priority=0,
               deadline_ms=None):
        """Route one request to ``model``: admission-check it against
        the model's overload watermarks (typed OverloadedError with a
        retry-after hint when the queue is past them), ensure the model
        is resident under the HBM budget (transparently reloading it /
        evicting LRU peers — the caller never sees the arbitration,
        only the latency), and enqueue on its engine.  ``priority`` /
        ``deadline_ms`` ride through to the engine's deadline scheduler
        (ISSUE 8).  Returns the engine's InferenceRequest future — its
        ``breakdown()`` carries the routed request's per-stage latency
        INCLUDING the arbitration window paid here (the trace context
        is attached before engine.submit, so the engine threads the
        registry's trace id instead of minting its own)."""
        self._check_admission(model)
        ctx = _trace.TraceContext()
        t0 = time.time()
        entry = self._ensure_resident(model)
        ctx.add_stage('arbitration', time.time() - t0)
        now = time.time()
        with self._lock:
            entry.requests += 1
            if entry.first_req_t is None:
                entry.first_req_t = now
            entry.last_req_t = now
        with _trace.attach(ctx):
            req = entry.engine.submit(feed, return_numpy=return_numpy,
                                      priority=priority,
                                      deadline_ms=deadline_ms)
        if req.rows:
            with self._lock:
                entry.rows += req.rows
        return req

    def infer(self, model, feed, return_numpy=True, timeout=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(model, feed,
                           return_numpy=return_numpy).result(timeout)

    def submit_generate(self, model, feed, max_len=None, priority=0,
                        deadline_ms=None):
        """Route one GENERATION request (ISSUE 7): admission-check the
        overload watermarks, ensure the model AND its decode cache are
        resident under the HBM budget, then enqueue on its engine's
        decode lane.  ``priority`` / ``deadline_ms`` ride the prefill
        lot and the decode lane's step-boundary deadline check (ISSUE
        8).  Returns the engine's GenerationRequest future; its
        ``breakdown()`` carries the arbitration window plus the
        prefill/decode/detokenize stages."""
        self._check_admission(model)
        ctx = _trace.TraceContext()
        t0 = time.time()
        entry = self._ensure_resident(model, decode=True)
        ctx.add_stage('arbitration', time.time() - t0)
        now = time.time()
        with self._lock:
            entry.requests += 1
            if entry.first_req_t is None:
                entry.first_req_t = now
            entry.last_req_t = now
        with _trace.attach(ctx):
            req = entry.engine.submit_generate(feed, max_len=max_len,
                                               priority=priority,
                                               deadline_ms=deadline_ms)
        with self._lock:
            entry.rows += 1
        return req

    def generate(self, model, feed, max_len=None, timeout=None):
        """Synchronous convenience: submit_generate + wait."""
        return self.submit_generate(model, feed,
                                    max_len=max_len).result(timeout)

    # ---- start/stop ----------------------------------------------------

    def start(self):
        """Start every loaded model's worker (queued mode); models
        loaded later start automatically."""
        with self._lock:
            if self._closed:
                raise RuntimeError('registry is closed')
            self._started = True
            engines = [e.engine for e in self._models.values()]
        for eng in engines:
            eng.start()
        return self

    def stop(self):
        """Stop every engine (each drains its queue), then unregister
        the registry's metrics source."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            engines = [e.engine for e in self._models.values()]
        for eng in engines:
            eng.stop()
        _profiler.unregister_metrics_source(self._metrics_key,
                                            self._metrics_fn)

    close = stop

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ---- observability -------------------------------------------------

    def status(self):
        """One fleet snapshot: per-model residency, HBM account (bytes +
        whether it is the seed estimate or live-corrected), live device
        footprint, queue depth, and request tallies — plus the arbiter's
        budget line."""
        with self._lock:
            arb = self.arbiter.snapshot()
            out = {'budget_bytes': arb['budget_bytes'],
                   'resident_bytes': arb['resident_bytes'],
                   'models': {}}
            for name, entry in self._models.items():
                acct = arb['accounts'].get(name, {})
                out['models'][name] = {
                    'resident': acct.get('resident', False),
                    'hbm_bytes': acct.get('bytes', 0),
                    'account_source': acct.get('source'),
                    'device_footprint': entry.engine.device_footprint(),
                    'queue_depth': entry.engine.queue_depth(),
                    'requests': entry.requests,
                    'rows': entry.rows,
                    'dirname': entry.dirname,
                    'parallel': entry.engine._pe is not None,
                }
            return out

    def queue_depths(self):
        """Cheap per-model queue depths — the fleet replica's
        per-response load report (ISSUE 17): no arbiter snapshot, no
        device-footprint walk, just each engine's batcher depth."""
        with self._lock:
            entries = dict(self._models)
        return {name: entry.engine.queue_depth()
                for name, entry in entries.items()}

    def metrics(self):
        """Router + arbiter + per-model engine snapshots (this is what
        the profiler sidecar carries under the registry's source)."""
        with self._lock:
            entries = dict(self._models)
        arb = self.arbiter.snapshot()
        per_model = {}
        for name, entry in entries.items():
            snap = entry.engine.metrics()
            window = ((entry.last_req_t - entry.first_req_t)
                      if entry.requests > 1 and entry.first_req_t else None)
            snap['router'] = {
                'requests': entry.requests,
                'rows': entry.rows,
                'req_per_s': (round((entry.requests - 1) / window, 3)
                              if window else None),
                'overload_rejects': entry.overload_rejects,
            }
            per_model[name] = snap
        return {
            'models': per_model,
            'evictions': arb['evictions'],
            'reloads': arb['reloads'],
            'admission_rejects': arb['admission_rejects'],
            'overload_rejects': sum(e.overload_rejects
                                    for e in entries.values()),
            'budget_bytes': arb['budget_bytes'],
            'resident_bytes': arb['resident_bytes'],
            'audit': arb['audit'],
            'lru_order': arb['lru_order'],
        }
