"""Generation serving: the slot-based decode-state cache and its
request/spec types (ISSUE 7).

The reference serves generation one graph call per decode step per
request (the beam-search/decode ops of the Fluid op layer, driven by a
host loop) — on TPU that measures the ~100ms dispatch tunnel, not the
chip.  The engine's decode lane amortizes it the same way run_multi
amortized training steps, with three pieces living here:

  * **GenerationSpec** — the model contract: a PREFILL program (prompt
    feeds -> initial per-request decoder state) and a STEP program
    (current token + state -> next-token logits + next state), wired
    by name.  The step program must be row-independent (each slot's
    math touches only its own row — the same contract micro-batched
    forward serving already imposes), so a batched slot dispatch is
    token-identical to per-request decode.
  * **SlotStateCache** — S fixed slots of decoder state (KV/hidden)
    resident in HBM, plus the per-slot token/alive/step-budget leaves
    the in-jit decode scan carries.  Requests ADMIT into free slots at
    step boundaries and RELEASE on finish — continuous batching, no
    drain barrier.  Under chunked prefill (ISSUE 14) a slot can also
    be in the PREFILLING phase: zeroed slabs + a host-side position
    cursor, inert in decode scans (alive=False) while C-token chunk
    dispatches advance the partial state in place — the finishing
    chunk flips the slot to decoding on device.  The cache is a
    first-class ``HBMArbiter`` account in the registry
    (``<model>:decode-cache``): an idle generation model's slabs
    evict to host and re-stage transparently (mid-prefill too — the
    cursor is host state).
  * **GenerationRequest** — the future ``submit_generate`` returns;
    resolves to the generated token ids (EOS-terminated or cut at
    ``max_len``), with the PR-6 trace threading prefill/decode/
    detokenize stages and a ``decode_steps`` count.
"""

import threading

import numpy as np

from ..fluid.executor import _is_host_op
from .batcher import InferenceRequest

__all__ = ['GenerationSpec', 'SlotStateCache', 'GenerationRequest']


def _slot_shape(program, name, what):
    """The per-slot (batch-free) shape + dtype a step-program feed
    declares.  Slot state must be STATIC-shaped: the cache is one
    resident [S, ...] array per feed, so a dynamic non-batch dim has
    no single slab to allocate."""
    var = program.global_block().vars.get(name)
    if var is None:
        raise ValueError('%s: %r is not a variable of the step program'
                         % (what, name))
    shape = tuple(var.shape)
    trailing = tuple(int(d) for d in shape[1:])
    if any(d < 0 for d in trailing):
        raise ValueError(
            '%s: feed %r declares a dynamic non-batch dim %s — slot '
            'state needs a static per-slot shape (size the cache axis, '
            'e.g. the KV length, to its maximum)' % (what, name, shape))
    return trailing, var.np_dtype


class GenerationSpec(object):
    """The generation model contract the engine's decode lane serves.

    prefill_program: prompt feeds -> the initial per-request decoder
        state, ONE fetch per ``state`` + ``context`` feed (in that
        order).  Served through the engine's normal lot machinery, so
        prompts micro-batch, shape-bucket, and ride the trailing-dim
        (seq-len) ladder like any forward request.
    step_program: ``token_feed`` + state/context feeds -> ``logits``
        (argmax = next token, greedy) + one fetch per ``state`` feed.
        Must be host-op free and row-independent.
    state: ordered (step_feed_name, step_fetch_var) pairs — the
        decoder state that UPDATES every step (hidden vectors, KV
        caches, position counters).
    context: step feed names that are per-request but FROZEN during
        decode (e.g. encoder outputs); their initial values come from
        the prefill fetches after the state ones.
    start_id / end_id: BOS fed at the first step / EOS stop condition.
    max_len: default (and cap for) per-request generation budget.

    Prefill fetches may be NARROWER than the slot shape on trailing
    axes (a prompt's KV prefix vs the full cache length): admission
    zero-pads them up to the slab — the step program masks what it
    has not written, exactly like rung padding under @SEQLEN.
    """

    def __init__(self, prefill_program, step_program, prefill_feeds,
                 prefill_fetches, token_feed, logits, state,
                 context=(), start_id=0, end_id=1, max_len=32,
                 prompt_feed=None, prompt_len_feed=None, max_ctx=None,
                 chunk_program=None, chunk_token=None, chunk_len=None,
                 chunk_state=None, chunk_width=None):
        self.prefill_program = prefill_program
        self.step_program = step_program
        self.prefill_feeds = list(prefill_feeds)
        self.prefill_fetches = list(prefill_fetches)
        self.token_feed = str(token_feed)
        self.logits = logits
        if isinstance(state, dict):
            state = list(state.items())
        self.state = [(str(n), v) for n, v in state]
        self.context = [str(n) for n in context]
        self.start_id = int(start_id)
        self.end_id = int(end_id)
        self.max_len = int(max_len)
        if self.max_len < 1:
            raise ValueError('GenerationSpec: max_len must be >= 1')
        if not self.state:
            raise ValueError(
                'GenerationSpec: at least one state pair is required — '
                'a stateless step function has nothing to carry across '
                'decode steps')
        self.slot_feeds = [n for n, _ in self.state] + self.context
        if len(self.prefill_fetches) != len(self.slot_feeds):
            raise ValueError(
                'GenerationSpec: prefill_fetches (%d) must align with '
                'the state + context feeds (%d: %s) — one initial value '
                'each, in order' % (len(self.prefill_fetches),
                                    len(self.slot_feeds),
                                    self.slot_feeds))
        for prog, label in ((prefill_program, 'prefill_program'),
                            (step_program, 'step_program')):
            if any(_is_host_op(op) for op in prog.global_block().ops):
                raise ValueError(
                    'GenerationSpec: %s contains host ops and cannot '
                    'run inside the decode lane' % label)
        # per-slot slab shapes/dtypes, from the step program's own feed
        # declarations (they key the cache allocation AND the admission
        # padding)
        self.slot_shapes = {}
        self.slot_dtypes = {}
        for name in self.slot_feeds + [self.token_feed]:
            shape, dtype = _slot_shape(step_program, name,
                                       'GenerationSpec')
            self.slot_shapes[name] = shape
            self.slot_dtypes[name] = dtype
        # ---- prompt identity + the decode-context bound (ISSUE 14) ----
        # prompt_feed names WHICH prefill feed carries the raw token
        # sequence (the chunked lane slices it into C-token blocks, and
        # the over-length typed reject measures it); prompt_len_feed
        # names its explicit length feed for dense prompts (LoD prompts
        # carry lengths in their LoD).  max_ctx is the decode KV
        # context bound: a prompt (or prompt + generation budget) past
        # it would scatter off the slab — submit_generate rejects it
        # typed instead of surfacing an opaque XLA error mid-prefill.
        self.prompt_feed = (str(prompt_feed)
                            if prompt_feed is not None else None)
        self.prompt_len_feed = (str(prompt_len_feed)
                                if prompt_len_feed is not None else None)
        self.max_ctx = int(max_ctx) if max_ctx is not None else None
        # ---- chunked prefill contract (ISSUE 14) ----------------------
        self.chunk_program = chunk_program
        self.chunk_token = (str(chunk_token)
                            if chunk_token is not None else None)
        self.chunk_len = str(chunk_len) if chunk_len is not None else None
        if isinstance(chunk_state, dict):
            chunk_state = list(chunk_state.items())
        self.chunk_state = ([(str(n), v) for n, v in chunk_state]
                            if chunk_state is not None else None)
        self.chunk_width = (int(chunk_width)
                            if chunk_width is not None else None)
        if chunk_program is not None:
            if self.chunk_token is None or self.chunk_state is None or \
                    self.chunk_width is None:
                raise ValueError(
                    'GenerationSpec: a chunk program needs chunk_token, '
                    'chunk_state and chunk_width alongside it')
            if self.prompt_feed is None:
                raise ValueError(
                    'GenerationSpec: chunked prefill needs prompt_feed '
                    '— the engine must slice the raw token sequence '
                    'into chunk blocks')
            if self.context:
                raise ValueError(
                    'GenerationSpec: chunked prefill does not support '
                    'context feeds — a chunk advances only the decode '
                    'STATE slabs, so frozen per-request context has no '
                    'chunk to initialize it')
            if [n for n, _ in self.chunk_state] != \
                    [n for n, _ in self.state]:
                raise ValueError(
                    'GenerationSpec: chunk_state must advance exactly '
                    'the decode state feeds, in order (%s vs %s)'
                    % ([n for n, _ in self.chunk_state],
                       [n for n, _ in self.state]))
            if any(_is_host_op(op)
                   for op in chunk_program.global_block().ops):
                raise ValueError(
                    'GenerationSpec: chunk_program contains host ops '
                    'and cannot run inside the decode lane')
            from ..fluid.shape_policy import bucketed_len
            if bucketed_len(self.chunk_width) != self.chunk_width:
                raise ValueError(
                    'GenerationSpec: chunk_width %d is not a seq-len '
                    'ladder rung — build the model with a rung-'
                    'quantized chunk (shape_policy.bucketed_len)'
                    % self.chunk_width)
            for name, _ in self.state:
                shape, dtype = _slot_shape(chunk_program, name,
                                           'GenerationSpec chunk')
                if shape != self.slot_shapes[name] or \
                        dtype != self.slot_dtypes[name]:
                    raise ValueError(
                        'GenerationSpec: chunk program declares state '
                        'feed %r as %s %s, step program as %s %s — the '
                        'chunk must advance the SAME slabs'
                        % (name, shape, dtype, self.slot_shapes[name],
                           self.slot_dtypes[name]))

    @property
    def supports_chunked_prefill(self):
        return self.chunk_program is not None

    def chunk_arg(self):
        """The ``chunk=`` dict run_chunk_prefill takes (ISSUE 14)."""
        return {'token': self.chunk_token, 'len': self.chunk_len,
                'state': list(self.chunk_state),
                'start_id': self.start_id}

    def prompt_ids(self, feed):
        """(token ids [L] int64, L) of one request's prompt, read from
        the ORIGINAL submit feed: an LoD prompt carries its length in
        the LoD, a dense one in ``prompt_len_feed`` (falling back to
        its full padded extent)."""
        if self.prompt_feed is None:
            raise ValueError(
                'GenerationSpec: no prompt_feed declared — the model '
                'dict must name which prefill feed carries the prompt '
                'tokens')
        from ..fluid import core
        v = feed[self.prompt_feed]
        if isinstance(v, core.LoDTensor) and v.lod():
            ids = np.asarray(v.numpy()).reshape(-1)
            return ids.astype(np.int64), int(ids.shape[0])
        arr = np.asarray(v.numpy() if isinstance(v, core.LoDTensor)
                         else v)
        flat = arr.reshape(-1)
        length = int(flat.shape[0])
        if self.prompt_len_feed is not None and \
                self.prompt_len_feed in feed:
            lv = feed[self.prompt_len_feed]
            length = int(np.asarray(
                lv.numpy() if isinstance(lv, core.LoDTensor) else lv
            ).reshape(-1)[0])
        return flat[:length].astype(np.int64), length

    @classmethod
    def from_model(cls, model, max_len=None):
        """Build a spec from the dict contract the model zoo's
        ``build_step_decode`` builders return (prefill/step programs,
        feed/fetch wiring, token ids, and — when the model was built
        with ``chunk=C`` — the chunked-prefill programs)."""
        return cls(model['prefill'], model['step'],
                   model['prefill_feeds'], model['prefill_fetches'],
                   model['token'], model['logits'], model['state'],
                   context=model.get('context', ()),
                   start_id=model['start_id'], end_id=model['end_id'],
                   max_len=(model['max_len'] if max_len is None
                            else max_len),
                   prompt_feed=model.get('prompt'),
                   prompt_len_feed=model.get('prompt_len'),
                   max_ctx=model.get('max_ctx'),
                   chunk_program=model.get('chunk'),
                   chunk_token=model.get('chunk_token'),
                   chunk_len=model.get('chunk_len'),
                   chunk_state=model.get('chunk_state'),
                   chunk_width=model.get('chunk_width'))

    def decode_arg(self):
        """The ``decode=`` dict run_decode_multi takes."""
        return {'token': self.token_feed, 'logits': self.logits,
                'state': list(self.state), 'context': list(self.context),
                'end_id': self.end_id}

    def cache_nbytes(self, slots):
        """The slot cache's HBM bytes at ``slots`` slots — computable
        BEFORE allocation (the arbiter's admission seed for the
        ``<model>:decode-cache`` account)."""
        total = 0
        for name in self.slot_feeds:
            shape = (int(slots), ) + self.slot_shapes[name]
            total += int(np.prod(shape)) * \
                np.dtype(self.slot_dtypes[name]).itemsize
        # token [S, 1] + alive [S] + remaining [S]
        total += int(slots) * (
            np.dtype(self.slot_dtypes[self.token_feed]).itemsize + 1 + 4)
        return total


class GenerationRequest(InferenceRequest):
    """One ``submit_generate`` future: resolves to the generated token
    ids (int64 ndarray; EOS-terminated, or cut at ``max_len``).  The
    prompt rides the prefill lot exactly like a forward request; then
    the request occupies ONE decode slot until its stop condition
    masks it out inside the scan."""

    kind = 'generate'

    def __init__(self, feed, rows, sig, max_len, return_numpy=True,
                 trace=None, priority=0, deadline_ms=None):
        super(GenerationRequest, self).__init__(
            feed, rows, sig, return_numpy=return_numpy, trace=trace,
            priority=priority, deadline_ms=deadline_ms)
        self.max_len = int(max_len)
        self.tokens = []
        self.slot = None
        # chunked prefill (ISSUE 14): the raw prompt token sequence the
        # engine slices into C-token blocks, and the phase flag — a
        # PREFILLING request occupies a slot whose slabs hold partial
        # state (alive=False in the carry, so decode scans freeze it)
        # until its finishing chunk dispatches
        self.prompt_tokens = None
        self.prompt_len = None
        self.prefilling = False


class SlotStateCache(object):
    """S fixed decode slots resident in HBM: one [S, ...] slab per
    state/context feed plus the scan-carry leaves (token/alive/
    remaining).  Slot ADMISSION writes a request's prefilled state into
    a free row (zero-padding narrow trailing axes up to the slab);
    RELEASE frees the row for the next admission — both at step
    boundaries, which is all continuous batching needs.

    Array leaves are swapped whole-reference by the owning engine's
    decode cycle (single worker thread, or the inline lock); the small
    host-side slot map is lock-guarded so the watchdog's snapshot can
    race a cycle safely."""

    def __init__(self, spec, slots, multiple=1):
        if int(slots) < 1:
            raise ValueError('SlotStateCache: slots must be >= 1')
        multiple = max(int(multiple), 1)
        # round UP to the mesh's dp extent: sharded decode needs the
        # slot dim divisible over the batch axis
        self.slots = -(-int(slots) // multiple) * multiple
        self.spec = spec
        self._lock = threading.Lock()
        self._init_state()

    def _init_state(self):
        """Fresh host-side slabs + carry leaves + an all-free slot map
        — shared by construction and reset() so the two can never
        drift on the slot-state layout."""
        s = self.slots
        spec = self.spec
        self._slabs = {
            name: np.zeros((s, ) + spec.slot_shapes[name],
                           spec.slot_dtypes[name])
            for name in spec.slot_feeds
        }
        self._token = np.full((s, 1), spec.end_id,
                              spec.slot_dtypes[spec.token_feed])
        self._alive = np.zeros((s, ), bool)
        self._remaining = np.zeros((s, ), np.int32)
        with self._lock:
            self._requests = [None] * s
            self._free = list(range(s))
            # chunked prefill (ISSUE 14): slot -> prompt position
            # cursor for slots in the PREFILLING phase (partial state
            # in the slabs, inert in decode scans)
            self._prefill = {}

    # ---- carry plumbing (the decode scan's view) -----------------------

    def carry(self):
        return {'slots': dict(self._slabs), 'token': self._token,
                'alive': self._alive, 'remaining': self._remaining}

    def set_carry(self, carry):
        self._slabs = dict(carry['slots'])
        self._token = carry['token']
        self._alive = carry['alive']
        self._remaining = carry['remaining']

    # ---- admission / release -------------------------------------------

    def free_slots(self):
        with self._lock:
            return len(self._free)

    def active_slots(self):
        with self._lock:
            return self.slots - len(self._free)

    def any_active(self):
        return self.active_slots() > 0

    @staticmethod
    def _write_row(arr, idx, row):
        if isinstance(arr, np.ndarray):
            arr = arr.copy() if not arr.flags.writeable else arr
            arr[idx] = row
            return arr
        return arr.at[idx].set(row)

    def admit(self, req, values):
        """Write one prefilled request into a free slot: ``values`` are
        the per-request prefill fetches ([1, ...] each, state + context
        order), zero-padded up to the slab's trailing shape.  Returns
        the slot index (the caller checked free_slots() first)."""
        with self._lock:
            if not self._free:
                raise RuntimeError('SlotStateCache: no free slot')
            idx = self._free.pop(0)
            self._requests[idx] = req
        for name, val in zip(self.spec.slot_feeds, values):
            row = np.asarray(val)
            if row.ndim >= 1 and row.shape[0] == 1:
                row = row[0]
            want = self.spec.slot_shapes[name]
            if row.shape != want:
                if len(row.shape) != len(want) or \
                        any(r > w for r, w in zip(row.shape, want)):
                    raise ValueError(
                        'decode admission: prefill value for %r has '
                        'shape %s, slot slab is %s — prefill fetches '
                        'must match the step program\'s declared state '
                        'shape (or be narrower on trailing axes)'
                        % (name, row.shape, want))
                padded = np.zeros(want, row.dtype)
                padded[tuple(slice(0, d) for d in row.shape)] = row
                row = padded
            self._slabs[name] = self._write_row(
                self._slabs[name], idx,
                row.astype(self.spec.slot_dtypes[name], copy=False))
        self._token = self._write_row(
            self._token, idx,
            np.asarray([self.spec.start_id],
                       self.spec.slot_dtypes[self.spec.token_feed]))
        self._alive = self._write_row(self._alive, idx, True)
        self._remaining = self._write_row(
            self._remaining, idx, np.int32(min(req.max_len,
                                               self.spec.max_len)))
        req.slot = idx
        return idx

    def admit_prefilling(self, req):
        """Admit one request into a free slot in the PREFILLING phase
        (ISSUE 14 — chunked prefill): every slab row zeroes (the chunk
        recurrence's initial state — both model families treat the
        all-zeros slab as position 0), the carry leaves go inert
        (token=end_id, alive=False, remaining=0 — decode scans freeze
        the slot), and the position cursor starts at 0.  The engine's
        chunk dispatches advance the slabs in place; the FINISHING
        chunk flips the slot to decoding on device.  Worker-thread
        only, at chain-flush points, like admit()."""
        with self._lock:
            if not self._free:
                raise RuntimeError('SlotStateCache: no free slot')
            idx = self._free.pop(0)
            self._requests[idx] = req
            self._prefill[idx] = 0
        for name in self.spec.slot_feeds:
            self._slabs[name] = self._write_row(
                self._slabs[name], idx,
                np.zeros(self.spec.slot_shapes[name],
                         self.spec.slot_dtypes[name]))
        self._token = self._write_row(
            self._token, idx,
            np.asarray([self.spec.end_id],
                       self.spec.slot_dtypes[self.spec.token_feed]))
        self._alive = self._write_row(self._alive, idx, False)
        self._remaining = self._write_row(self._remaining, idx,
                                          np.int32(0))
        req.slot = idx
        req.prefilling = True
        return idx

    def prefilling_items(self):
        """[(slot, request, cursor)] for every slot mid-prefill — the
        engine's chunk assembly reads this, the watchdog snapshot
        counts it."""
        with self._lock:
            return [(idx, self._requests[idx], cur)
                    for idx, cur in sorted(self._prefill.items())]

    def advance_prefill(self, idx, n):
        """Move one prefilling slot's cursor by ``n`` consumed prompt
        tokens (deterministic host mirror of the dispatched chunk —
        no device read needed)."""
        with self._lock:
            self._prefill[idx] += int(n)
            return self._prefill[idx]

    def finish_prefill(self, idx):
        """The slot's finishing chunk dispatched: leave the prefilling
        phase (the chunk kernel already flipped the carry to decoding
        on device)."""
        with self._lock:
            self._prefill.pop(idx, None)
        req = self.request_at(idx)
        if req is not None:
            req.prefilling = False

    def release(self, idx):
        with self._lock:
            req = self._requests[idx]
            self._requests[idx] = None
            self._free.append(idx)
            self._prefill.pop(idx, None)
        if req is not None:
            req.slot = None
        return req

    def deactivate(self, idx):
        """Mask one slot out of the scan NOW (a mid-generation shed,
        ISSUE 8): alive -> False, remaining -> 0, token -> end_id.
        ``release`` only frees the host-side slot map; without this the
        next decode dispatch would keep spending scan steps on a
        request that no longer has a caller.  Worker-thread only, like
        set_carry."""
        self._alive = self._write_row(self._alive, idx, False)
        self._remaining = self._write_row(self._remaining, idx,
                                          np.int32(0))
        self._token = self._write_row(
            self._token, idx,
            np.asarray([self.spec.end_id],
                       self.spec.slot_dtypes[self.spec.token_feed]))

    def reset(self):
        """Reinitialize every slab and carry leaf to the fresh host-
        side state and free every slot (ISSUE 9 — the chained lane's
        poisoned-carry recovery: after a failed dispatch/harvest the
        cache's carry references errored device values, so the engine
        errors the slotted requests and decodes the next admissions
        from clean slabs).  Worker-thread only, like set_carry."""
        self._init_state()

    def request_at(self, idx):
        with self._lock:
            return self._requests[idx]

    def active_requests(self):
        with self._lock:
            return [r for r in self._requests if r is not None]

    # ---- accounting / observability ------------------------------------

    def nbytes(self):
        """Live bytes of every slab + carry leaf (host- or device-
        resident — the account tracks the slabs wherever they sit)."""
        total = 0
        for arr in list(self._slabs.values()) + [
                self._token, self._alive, self._remaining]:
            total += int(getattr(arr, 'nbytes', 0))
        return total

    def to_host(self):
        """Demote every slab to a host ndarray (bitwise — decode
        resumes exactly after re-staging).  Returns bytes moved."""
        moved = 0
        import jax
        for name, arr in list(self._slabs.items()):
            if isinstance(arr, jax.Array):
                self._slabs[name] = np.asarray(arr)
                moved += int(arr.nbytes)
        for attr in ('_token', '_alive', '_remaining'):
            arr = getattr(self, attr)
            if isinstance(arr, jax.Array):
                setattr(self, attr, np.asarray(arr))
                moved += int(arr.nbytes)
        return moved

    def snapshot(self):
        """The flight recorder's slot-map view: who holds each slot
        (trace ids), occupancy, and the cache's byte size — recorded on
        decode dispatches and dumped on worker errors / watchdog
        stalls."""
        with self._lock:
            return {
                'slots': self.slots,
                'active': self.slots - len(self._free),
                'free': len(self._free),
                'prefilling': len(self._prefill),
                'bytes': self.nbytes(),
                'slot_trace_ids': [
                    (r.trace_id if r is not None else None)
                    for r in self._requests
                ],
            }
