"""The TPU-native inference serving engine.

The reference ships inference as a per-request ABI
(paddle_inference_api.h: PaddlePredictor.Run — one graph execution per
call).  On TPU every dispatch rides a ~100ms tunnel round trip
(MFU_BOUND_r03), so a request-per-dispatch server measures the tunnel,
not the chip.  This engine amortizes the same way Executor.run_multi
does for training, behind a request-facing surface:

  1. **dynamic micro-batching** — submitted requests coalesce in a
     MicroBatcher up to max_batch_size rows / a max_wait deadline;
  2. **shape bucketing** — each lot pads (masked, replicated last real
     row — the @SAMPLE_MASK machinery) to a bounded ShapeBucketSet
     ladder entry, so wandering request sizes map to a small fixed set
     of XLA executables; fetches trim back to real row counts.  The
     TRAILING dims bucket too (ISSUE 5, TrailingDimBuckets): variable
     seq-len/resolution extents quantize onto the shared
     fluid.shape_policy ladder (LoD feeds lower to padded + @SEQLEN at
     submit), so mixed-length requests coalesce instead of fragmenting
     into per-shape lots and per-shape executables; per-request fetches
     trim back to real trailing extents;
  3. **pipelined multi-step eval dispatch** — up to steps_per_dispatch
     same-bucket lots ship as ONE Executor.run_eval_multi scan (K eval
     batches per dispatch, donated scanned block), and up to
     pipeline_depth dispatches stay in flight so host feed/fetch
     overlaps device compute; dp>1 serving shards lots batch-dim over
     the mesh via ParallelExecutor.run_eval_multi;
  4. **metrics** — queue depth, batch fill ratio, p50/p99 latency,
     dispatch/compile counts, surfaced through fluid.profiler's
     timeline sidecar so tools/timeline.py renders serving spans.

Synchronous use needs no thread: an engine that was never ``start()``ed
dispatches inline on the submitter's thread (fluid.Inferencer runs this
mode).  ``start()`` spawns the worker loop for the queued mode.
"""

import contextlib
import threading
import time
import weakref
from collections import deque

import numpy as np

from ..fluid import core
from ..fluid import profiler as _profiler
from ..fluid import trace as _trace
from ..fluid.executor import Executor, feed_signature, _is_host_op, \
    fetch_batch_led, prepare_feed_arrays
from ..ops.registry import SEQLEN_SUFFIX, ROWS_SUFFIX, SAMPLE_MASK_NAME
from ..fluid.parallel_executor import ParallelExecutor, pad_ragged_batch, \
    _lead
from .batcher import InferenceRequest, MicroBatcher
from .buckets import ShapeBucketSet, TrailingDimBuckets
from .errors import DeadlineExceededError, EngineClosedError
from .metrics import EngineMetrics, RateWindow
from .profile import ServiceTimeProfile

__all__ = ['ServingConfig', 'InferenceEngine']

_ENGINE_SEQ = [0]
_ENGINE_SEQ_LOCK = threading.Lock()


class ServingConfig(object):
    """Engine knobs (documented in README 'Serving engine').

    max_batch_size: rows per lot before a full flush.
    max_wait_ms: oldest-request age forcing a deadline flush — the
        latency bound at low traffic.
    steps_per_dispatch: max same-bucket lots per run_eval_multi scan.
    pipeline_depth: dispatches kept in flight before the worker blocks
        on the oldest one's results (2 = double buffering).
    bucket_sizes: explicit ladder for the ShapeBucketSet (None = powers
        of two up to max_batch_size).
    max_buckets: bound on the active bucket set (LRU accounting).
    trailing_buckets: quantize variable TRAILING dims onto the shared
        seq-len ladder (fluid.shape_policy — the same policy the
        executor applies to LoD max-lens), so mixed-length sequence
        requests share a signature and coalesce: single-level LoD
        feeds lower to padded [B, T, ...] + @SEQLEN at submit, and
        PaddedSequence data re-pads to its rung.  Padded positions are
        masked by the @SEQLEN lowerings, so batched results stay
        bitwise-equal to per-request runs.  False restores the old
        behavior (every LoD/PaddedSequence request is its own
        unbatchable lot).
    trailing_ladders: EXPLICIT per-feed trailing ladders for DENSE
        feeds — ``{'img': [224, 256]}`` (axis 1) or
        ``{'img': {2: [224, 256], 3: [224, 256]}}`` (named axes): the
        resolution-ladder opt-in.  The engine zero-pads those axes up
        to the covering rung; because a dense feed carries no @SEQLEN
        masking contract, this is only output-preserving for models
        that ignore trailing padding (masked pooling/attention, padded
        detection inputs) — opting in asserts that.
    max_trailing_buckets: bound on the active trailing set (LRU
        accounting, like max_buckets for the batch ladder).
    watchdog_stall_s: queue-age stall threshold (seconds) for the
        trace watchdog (ISSUE 6) — a started engine registers a probe
        over its oldest queued request's age; crossing the threshold
        dumps the flight recorder (the post-mortem of a stuck worker).
        None (default) registers no probe.
    decode_slots: slot count of the generation lane's resident decode
        cache (ISSUE 7) — the continuous-batching degree.  Rounded UP
        to the mesh's dp extent for sharded serving.  Only meaningful
        when the engine was built with ``generation=``.
    decode_steps: decode-scan steps per device dispatch (the K of the
        in-jit greedy loop) — the generation lane's dispatch-tax
        amortizer, bounded below the per-request latency a step
        boundary adds to admission.
    prefill_chunk: chunked prefill (ISSUE 14) — split every prompt
        into C-token blocks and interleave them with decode scans
        under DECODE PRIORITY, so the max decode inter-token stall a
        long prompt can impose is ONE chunk's wall, not the whole
        prompt's.  The value is quantized up to the shared seq-len
        rung ladder (fluid.shape_policy) and must match the chunk
        width the generation model was built with
        (``build_step_decode(chunk=C)``); requests admit into a
        ``prefilling`` decode slot (partial state in the slabs, inert
        in decode scans) and each worker cycle rides AT MOST one chunk
        dispatch, budgeted by the measured chunk wall against the
        earliest active decode deadline's headroom (ServiceTimeProfile
        — a chunk that would push the next step boundary past an
        imminent deadline waits a cycle).  Chunked prefill is EXACT:
        generated tokens are identical to the monolithic lane for both
        model families (the chunk programs chain bitwise).  None (the
        default) keeps the monolithic PR 9 prefill-lot lane bitwise.
    decode_pipeline_depth: decode scans kept in flight (ISSUE 9 — the
        decode lane's pipeline_depth).  At 2 (the default) scan N+1 is
        enqueued against scan N's device-resident output carry BEFORE
        N's token block is harvested, so the host's detokenize/EOS/
        release bookkeeping overlaps device compute and the device
        never idles on a host round trip between scans; admission,
        eviction and shedding happen at chain-FLUSH points (every
        in-flight scan harvested first), keeping outputs
        token-identical to the per-scan-sync lane.  1 restores that
        lane exactly: dispatch, sync, bookkeep, dispatch — one
        device-idling host sync per scan (the baseline side of the
        ``decode_overlap`` perf gate).
    adaptive_admission: scale the overload admission watermarks by the
        measured queue-drain rate vs the arrival rate (ISSUE 9) — an
        engine whose drain keeps up with arrivals tolerates a deeper
        queue (burst absorption, up to 2x the static watermark); one
        falling behind admits at a proportionally SHALLOWER depth
        (down to half), shedding load before the queue is hopeless.
        Only meaningful with admit_queue_depth/admit_queue_age_ms set;
        False (the PR 8 default) keeps the watermarks static.
    scheduling: 'edf' (default) — deadline-aware lot formation (ISSUE
        8): highest priority first, earliest-deadline-first within a
        priority class, and past-deadline (or no-longer-meetable)
        requests SHED with a typed DeadlineExceededError instead of
        served late.  Requests without priorities/deadlines keep exact
        FIFO order, so the default changes nothing for pre-SLO
        callers.  'fifo' restores strict arrival order with no
        shedding — the baseline side of the ``slo`` perf gate.
    priority_aging_ms: starvation escape hatch for strict priority
        (ISSUE 11 satellite; ROADMAP item 5 leftover).  Under EDF a
        saturated high-priority stream starves a low class FOREVER;
        with aging set, each full window a request has waited promotes
        its EFFECTIVE class by one at lot formation (a request aging
        ``k`` windows competes as ``priority + k``), so starving
        low-priority work eventually outranks fresh high-priority
        arrivals.  Promotion engages only BELOW the highest pending
        real class — a class alone in the queue keeps pure EDF order
        (aging never cuts an undeadlined request ahead of a
        deadline-imminent peer of its own class).  None (default)
        keeps strict priority.
    shed_by_class: load-shedding by priority CLASS (ISSUE 12
        satellite; ROADMAP item 5 leftover).  The default shed rule
        judges each deadlined request against its OWN service estimate
        only; under overload that serves doomed low-class work at the
        expense of meetable high-class work.  With shed_by_class the
        shed pass walks the queue in scheduling order (highest class
        first, EDF within a class) ACCUMULATING the service estimates
        of everything ahead — a deadlined request sheds when the
        backlog in front of it already pushes its finish past the
        deadline, so the lowest-priority-class deadlined work sheds
        FIRST (it is served last, so the backlog dooms it first).
        Same-class EDF order is untouched (pinned).  EDF only.
    admit_queue_depth / admit_queue_age_ms: per-model admission
        watermarks the ModelRegistry enforces at ROUTING time — a
        request routed while the engine's queue is at least this deep
        (or its oldest queued request at least this old) is refused
        with a typed OverloadedError carrying a retry-after hint,
        instead of queueing toward certain deadline death.  None
        (default) disables that watermark; direct engine.submit()
        callers are never admission-checked (the registry is the
        fleet's front door).
    """

    def __init__(self, max_batch_size=32, max_wait_ms=5.0,
                 steps_per_dispatch=4, pipeline_depth=2,
                 bucket_sizes=None, max_buckets=16,
                 trailing_buckets=True, trailing_ladders=None,
                 max_trailing_buckets=32, watchdog_stall_s=None,
                 decode_slots=8, decode_steps=4, decode_pipeline_depth=2,
                 prefill_chunk=None, scheduling='edf',
                 admit_queue_depth=None, admit_queue_age_ms=None,
                 adaptive_admission=False, priority_aging_ms=None,
                 shed_by_class=False):
        if int(steps_per_dispatch) < 1:
            raise ValueError('steps_per_dispatch must be >= 1')
        if int(pipeline_depth) < 1:
            raise ValueError('pipeline_depth must be >= 1')
        if int(max_buckets) < 1:
            raise ValueError('max_buckets must be >= 1')
        if int(max_trailing_buckets) < 1:
            # a 0 bound would make every bucket_for miss insert-then-
            # evict its own key: an always-empty active set and an
            # evictions counter equal to the miss count
            raise ValueError('max_trailing_buckets must be >= 1')
        self.max_batch_size = int(max_batch_size)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.steps_per_dispatch = int(steps_per_dispatch)
        self.pipeline_depth = int(pipeline_depth)
        self.bucket_sizes = bucket_sizes
        self.max_buckets = int(max_buckets)
        if trailing_ladders and not trailing_buckets:
            raise ValueError(
                'ServingConfig: trailing_ladders= requires trailing '
                'bucketing — drop trailing_buckets=False, or drop the '
                'ladders')
        self.trailing_buckets = bool(trailing_buckets)
        self.trailing_ladders = trailing_ladders
        self.max_trailing_buckets = int(max_trailing_buckets)
        self.watchdog_stall_s = (float(watchdog_stall_s)
                                 if watchdog_stall_s is not None else None)
        if int(decode_slots) < 1:
            raise ValueError('decode_slots must be >= 1')
        if int(decode_steps) < 1:
            raise ValueError('decode_steps must be >= 1')
        self.decode_slots = int(decode_slots)
        self.decode_steps = int(decode_steps)
        if int(decode_pipeline_depth) < 1:
            raise ValueError('decode_pipeline_depth must be >= 1 '
                             '(1 = the per-scan-sync lane)')
        self.decode_pipeline_depth = int(decode_pipeline_depth)
        if prefill_chunk is not None:
            if int(prefill_chunk) < 1:
                raise ValueError('prefill_chunk must be >= 1 (or None '
                                 'for monolithic prefill)')
            from ..fluid.shape_policy import bucketed_len
            prefill_chunk = bucketed_len(int(prefill_chunk))
        self.prefill_chunk = prefill_chunk
        self.adaptive_admission = bool(adaptive_admission)
        if scheduling not in ('edf', 'fifo'):
            raise ValueError(
                "ServingConfig: scheduling must be 'edf' or 'fifo', "
                'got %r' % (scheduling, ))
        self.scheduling = scheduling
        if priority_aging_ms is not None and float(priority_aging_ms) <= 0:
            raise ValueError('priority_aging_ms must be > 0 (or None '
                             'for strict priority)')
        if priority_aging_ms is not None and scheduling == 'fifo':
            raise ValueError(
                'ServingConfig: priority_aging_ms only applies to EDF '
                "scheduling — drop scheduling='fifo', or drop the aging "
                'window')
        self.priority_aging_s = (float(priority_aging_ms) / 1e3
                                 if priority_aging_ms is not None else None)
        if shed_by_class and scheduling == 'fifo':
            raise ValueError(
                'ServingConfig: shed_by_class only applies to EDF '
                "scheduling — drop scheduling='fifo', or drop "
                'shed_by_class')
        self.shed_by_class = bool(shed_by_class)
        if admit_queue_depth is not None and int(admit_queue_depth) < 1:
            raise ValueError('admit_queue_depth must be >= 1 (or None '
                             'to disable the depth watermark)')
        if admit_queue_age_ms is not None and \
                float(admit_queue_age_ms) <= 0:
            raise ValueError('admit_queue_age_ms must be > 0 (or None '
                             'to disable the age watermark)')
        self.admit_queue_depth = (int(admit_queue_depth)
                                  if admit_queue_depth is not None
                                  else None)
        self.admit_queue_age_s = (float(admit_queue_age_ms) / 1e3
                                  if admit_queue_age_ms is not None
                                  else None)
        if self.adaptive_admission and self.admit_queue_depth is None \
                and self.admit_queue_age_s is None:
            raise ValueError(
                'ServingConfig: adaptive_admission needs a watermark '
                'to adapt — set admit_queue_depth and/or '
                'admit_queue_age_ms, or drop adaptive_admission')


class _Lot(object):
    """One padded, bucket-shaped batch of coalesced requests.
    ``kind`` ('forward' | 'generate') routes the dispatch: forward lots
    run the engine's program, generate lots run the generation spec's
    PREFILL program and their results admit into decode slots."""

    __slots__ = ('requests', 'feed', 'real', 'bucket', 'sig', 'kind')

    def __init__(self, requests, feed, real, bucket, sig, kind='forward'):
        self.requests = requests
        self.feed = feed
        self.real = real  # None for an unbatchable (LoD) lot
        self.bucket = bucket
        self.sig = sig
        self.kind = kind


class InferenceEngine(object):
    """Serve a loaded inference program (fluid.io.load_inference_model)
    through micro-batched, bucketed, pipelined eval dispatches."""

    def __init__(self, program, feed_names=None, fetch_list=None,
                 place=None, scope=None, executor=None, parallel=False,
                 mesh=None, config=None, name=None, generation=None,
                 embed_caches=None):
        if fetch_list is None:
            raise ValueError('InferenceEngine: fetch_list is required '
                             '(the fetch targets returned by '
                             'load_inference_model)')
        self._program = program
        self._feed_names = list(feed_names) if feed_names else None
        self._fetch_list = list(fetch_list)
        # static axis-1 widths of the fetch targets: a fetch of such a
        # width (a class/hidden axis — fc(.., 16) under a 16 rung) can
        # NOT be a mirrored rung-padded seq axis, so _bucket_trailing
        # voids any rung coinciding with one (same reasoning as the
        # static-feed guard there); dynamic seq fetches carry -1 on
        # axis 1 and stay trimmable
        self._fetch_static_ax1 = set()
        for v in self._fetch_list:
            shape = tuple(getattr(v, 'shape', None) or ())
            if len(shape) >= 2 and int(shape[1]) > 0:
                self._fetch_static_ax1.add(int(shape[1]))
        self._scope = scope if scope is not None else core.Scope()
        self.config = config if config is not None else ServingConfig()
        # host ops (save/print/readers) cannot run inside the eval scan:
        # such programs serve EAGERLY — one exe.run per request, no
        # padding/coalescing — preserving the Executor's per-step host-
        # op semantics (the pre-engine Inferencer behavior)
        self._eager = any(_is_host_op(op)
                          for op in program.global_block().ops)
        # two-tier embedding stores (ISSUE 12): inference lookups hit
        # the SAME hot-row slab training uses — the worker remaps each
        # lot's id feeds to slab slots and applies the row exchange
        # (misses fetch from the host master; inference stages are
        # never dirty, so its evictions write nothing back).
        # Validated HERE, before any generation/PE machinery builds:
        # the unsupported combinations must fail fast and leak nothing.
        self._embed_caches = list(embed_caches or [])
        if self._embed_caches and self._eager:
            raise NotImplementedError(
                'embed_caches cannot serve host-op (eager) programs — '
                'the per-request exe.run path has no lot to stage an '
                'exchange for')
        if self._embed_caches and generation is not None:
            # the prefill lots and decode-step dispatches do not remap
            # id feeds to slab slots: raw vocab ids against the [C, D]
            # slab would silently gather wrong rows — reject the
            # combination until the generation lane learns to stage
            raise NotImplementedError(
                'embed_caches cannot serve generation= engines yet — '
                'the prefill/decode dispatch paths do not remap lookup '
                'ids to slab slots')
        for _cache in self._embed_caches:
            _cache.check_scope(self._scope, 'InferenceEngine')
        self._pe = None
        if parallel or mesh is not None:
            if self._eager:
                raise NotImplementedError(
                    'sharded serving cannot run host-op programs — '
                    'remove the host ops or serve with parallel=False')
            self._pe = ParallelExecutor(main_program=program,
                                        scope=self._scope, mesh=mesh)
            multiple = self._pe._dp_extent()
        else:
            multiple = 1
        place = place if place is not None else (
            core.TPUPlace() if core.is_compiled_with_tpu()
            else core.CPUPlace())
        self._exe = executor if executor is not None else Executor(place)
        self.buckets = ShapeBucketSet(self.config.max_batch_size,
                                      sizes=self.config.bucket_sizes,
                                      multiple=multiple,
                                      max_buckets=self.config.max_buckets)
        # the trailing-dim twin (ISSUE 5): None when disabled (or for
        # eager host-op programs, whose per-request exe.run path never
        # coalesces anyway)
        self.trailing = None
        if self.config.trailing_buckets and not self._eager:
            self.trailing = TrailingDimBuckets(
                ladders=self.config.trailing_ladders,
                max_buckets=self.config.max_trailing_buckets)
        # deadline-aware lot formation (ISSUE 8): the engine owns the
        # shed side effects (typed error + 'shed' trace stage + the
        # counter), and feeds the batcher its service estimate so
        # hopeless requests shed BEFORE burning a dispatch.  The
        # estimate is 3x the MINIMUM recent dispatch wall: min, not
        # mean — a compile-heavy cold dispatch (hundreds of ms) would
        # poison a mean into shedding EVERYTHING under tight deadlines,
        # and a total shed stops drains, so a poisoned mean could never
        # recover; min bounds the true service floor.  The 3x margin
        # matters because EDF always picks the most at-risk request:
        # with only ~1 dispatch-wall of slack the pick lands AT the
        # deadline and timing jitter turns it late — 3x leaves a full
        # dispatch of slack after the pick.
        # ISSUE 9 sharpens WHICH wall: the horizon is now per
        # SIGNATURE (ServiceTimeProfile, min-of-recent-walls per
        # coalescing sig, cost-registry seeded) — a mixed-shape queue
        # sheds the slow-signature request the global minimum would
        # have admitted; unseen signatures fall back to the global
        # floor, which is exactly the old estimator.
        ref0 = weakref.ref(self)
        self._service_walls = deque(maxlen=8)
        self._profile = ServiceTimeProfile()
        self._batcher = MicroBatcher(
            self.config.max_batch_size, self.config.max_wait_s,
            scheduling=self.config.scheduling,
            on_shed=lambda req: (ref0() and ref0()._shed_request(req)),
            service_estimate_for=lambda req: (
                ref0()._service_estimate(req) if ref0() else 0.0),
            priority_aging_s=self.config.priority_aging_s,
            shed_by_class=self.config.shed_by_class)
        # arrival vs drain rates (ISSUE 9): the adaptive admission
        # watermarks' inputs — noted at submit and at delivery
        self._arrivals = RateWindow()
        self._drains = RateWindow()
        # generation lane (ISSUE 7): a GenerationSpec turns on
        # submit_generate — prompts prefill through the normal lot
        # machinery, then decode in the slot-batched in-jit scan
        self.generation = generation
        self._decode_cache = None
        self._gen_ready = deque()  # (request, prefill values) awaiting a slot
        # pipelined decode chain (ISSUE 9/14): in-flight dispatches not
        # yet harvested, kind-tagged — ('decode', toks_dev,
        # alive_in_dev, k, t_disp, slot->req snapshot, slot-map snap)
        # or ('chunk', ok_dev, None, width, t_disp, None, snap); FIFO =
        # device order, bounded by decode_pipeline_depth
        self._decode_inflight = deque()
        # raw scan walls (dispatch -> harvest sync) — the decode lane's
        # own service floor for per-token deadline estimates
        self._decode_walls = deque(maxlen=8)
        # chunked prefill (ISSUE 14): prompts awaiting a prefilling
        # slot, measured chunk walls (the decode-priority budget), and
        # the prefill-activity flag feeding the inter-token stall gauge
        self._chunk_pending = deque()
        self._chunk_walls = deque(maxlen=8)
        self._prefill_since_harvest = False
        self._last_harvest_t = None
        self._last_harvest_alive = frozenset()
        self._chunking = False
        self._pe_prefill = self._pe_step = self._pe_chunk = None
        if generation is None and self.config.prefill_chunk is not None:
            raise ValueError(
                'ServingConfig(prefill_chunk=) only applies to '
                'generation= engines — there is no prefill to chunk')
        if generation is not None:
            if self._eager:
                raise NotImplementedError(
                    'generation serving cannot run host-op programs — '
                    'the decode scan is pure compute')
            from .decode import SlotStateCache
            self._decode_cache = SlotStateCache(
                generation, self.config.decode_slots, multiple=multiple)
            self._gen_decode_arg = generation.decode_arg()
            if self.config.prefill_chunk is not None:
                if not generation.supports_chunked_prefill:
                    raise ValueError(
                        'ServingConfig(prefill_chunk=%d): this '
                        'generation model has no chunk program — build '
                        'it with build_step_decode(chunk=%d) (and run '
                        'its chunk_startup), or drop prefill_chunk'
                        % (self.config.prefill_chunk,
                           self.config.prefill_chunk))
                if generation.chunk_width != self.config.prefill_chunk:
                    raise ValueError(
                        'ServingConfig(prefill_chunk=%d) does not match '
                        'the model\'s chunk width %d — the chunk '
                        'executable\'s block shape is fixed at build '
                        'time' % (self.config.prefill_chunk,
                                  generation.chunk_width))
                self._gen_chunk_arg = generation.chunk_arg()
                self._chunking = True
            if self._pe is not None:
                # PE binds one program each: the prefill and step
                # programs get their own sharded executors over the
                # SAME mesh + scope (weights shared)
                self._pe_prefill = ParallelExecutor(
                    main_program=generation.prefill_program,
                    scope=self._scope, mesh=self._pe._mesh)
                self._pe_step = ParallelExecutor(
                    main_program=generation.step_program,
                    scope=self._scope, mesh=self._pe._mesh)
                if self._chunking:
                    self._pe_chunk = ParallelExecutor(
                        main_program=generation.chunk_program,
                        scope=self._scope, mesh=self._pe._mesh)
        self._metrics = EngineMetrics()
        self._inflight = deque()
        self._last_sync_t = 0.0  # previous drain's sync, clips MFU windows
        self._carry = deque()  # flushed lots awaiting a matching block
        self._inline_lock = threading.Lock()
        # the pause gate: the worker holds it for exactly one
        # collect->dispatch->drain cycle; paused() (the registry's
        # eviction window) holds it for the whole pause, excluding new
        # dispatches while weights move between device and host
        self._cycle_lock = threading.RLock()
        # cross-engine fair-dispatch turnstile: None = no gate (a lone
        # engine); the ModelRegistry shares ONE lock across its engines
        # so each device dispatch is a bounded critical section and no
        # model's worker can hog the device between another's dispatches
        self._gate = None
        self._thread = None
        self._closed = False
        self._warned_unsliced = False
        self._watchdog_probe = None
        self._watchdog_age_fn = None
        with _ENGINE_SEQ_LOCK:
            _ENGINE_SEQ[0] += 1
            seq = _ENGINE_SEQ[0]
        self.name = name or ('serving-engine-%d' % seq)
        # timeline spans are KEYED by engine name (serving/<name>/...):
        # two engines profiled in one window land in separate timeline
        # rows instead of interleaving in one anonymous ':serving' row
        self._spans = 'serving/%s/' % self.name
        # profiler sidecar: a weakly-bound metrics source, so profiled
        # runs dump the serving snapshot without keeping dead engines
        # alive (tools/timeline.py renders the spans; the sidecar's
        # 'metrics' block carries the counters).  The registry returns
        # the KEY the source landed under — a second engine reusing the
        # same name is uniquified (name#2), so neither snapshot is lost.
        ref = weakref.ref(self)
        self._metrics_fn = lambda: (ref().metrics() if ref() else None)
        self._metrics_key = _profiler.register_metrics_source(
            self.name, self._metrics_fn)
        # an inline-mode engine may never be stop()ped: drop its
        # registration at GC so the source table can't grow unbounded
        weakref.finalize(self, _profiler.unregister_metrics_source,
                         self._metrics_key, self._metrics_fn)

    @classmethod
    def from_saved_model(cls, dirname, place=None, model_filename=None,
                         params_filename=None, **kwargs):
        """Build an engine straight from a save_inference_model dir
        (own scope + executor; the request-facing analog of
        create_paddle_predictor)."""
        from ..fluid import io as fluid_io
        from ..fluid.executor import scope_guard
        place = place if place is not None else (
            core.TPUPlace() if core.is_compiled_with_tpu()
            else core.CPUPlace())
        exe = Executor(place)
        scope = core.Scope()
        with scope_guard(scope):
            program, feed_names, fetch_targets = \
                fluid_io.load_inference_model(
                    dirname, exe, model_filename=model_filename,
                    params_filename=params_filename)
        return cls(program, feed_names=feed_names,
                   fetch_list=fetch_targets, place=place, scope=scope,
                   executor=exe, **kwargs)

    # ---- lifecycle ----------------------------------------------------

    def start(self):
        """Spawn the worker thread (queued mode)."""
        if self._closed:
            raise RuntimeError('engine is closed')
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._serve_loop, name=self.name, daemon=True)
            self._thread.start()
            if self.config.watchdog_stall_s is not None and \
                    self._watchdog_probe is None:
                # a queued request aging past the threshold means the
                # worker is stuck — dump what was in flight before the
                # stall takes it to its grave.  WEAK closures, like the
                # metrics source: the global watchdog must not pin a
                # dropped engine (and its scope's device buffers) alive
                ref = weakref.ref(self)

                def age(ref=ref):
                    eng = ref()
                    return eng._batcher.oldest_age() if eng else None

                def ctx(ref=ref):
                    eng = ref()
                    return eng._stall_context() if eng else None

                self._watchdog_probe = _trace.watchdog.register(
                    'serving/%s/queue_age' % self.name, age,
                    self.config.watchdog_stall_s, context_fn=ctx)
                self._watchdog_age_fn = age
                # a started engine dropped without stop(): the probe
                # unregisters at GC (owner-checked — the key may have
                # been reused by a successor by then)
                weakref.finalize(self, _trace.watchdog.unregister,
                                 self._watchdog_probe, age)
                from ..distributed.embed_cache import register_stall_probe
                for cache in self._embed_caches:
                    # a late host row fetch stalls the worker exactly
                    # like a stuck queue — same threshold, its own
                    # prefetch-stall probe (ISSUE 12)
                    register_stall_probe(
                        self,
                        'serving/%s/embed_cache/%s/prefetch_stall'
                        % (self.name, cache.var),
                        cache, self.config.watchdog_stall_s)
        return self

    def _stall_context(self):
        """The stall dump's in-flight view: trace ids still queued (a
        stuck worker never dispatched them, so the ring has no record)
        plus those dispatched but not yet drained."""
        inflight = []
        try:
            for _, lots, _, _, _, _ in list(self._inflight):
                for lot in lots:
                    inflight.extend(r.trace_id for r in lot.requests)
        except RuntimeError:
            # a drain mutated the deque mid-snapshot (the watchdog
            # thread races the worker); the queued ids below are
            # independent and must still make the dump
            pass
        ctx = {'queued_trace_ids': self._batcher.pending_trace_ids(),
               'inflight_trace_ids': inflight}
        if self._decode_cache is not None:
            # the decode lane's view: who holds each slot (a stalled
            # worker strands THEM mid-generation), how many prefilled
            # requests were still waiting for one, and the in-flight
            # CHAIN (ISSUE 9) — scans dispatched but never harvested
            # are exactly what a wedged chained lane looks like
            ctx['decode_slot_map'] = self._decode_cache.snapshot()
            ctx['decode_pending'] = len(self._gen_ready) + \
                len(self._chunk_pending)
            now = time.time()
            try:
                ctx['decode_chain'] = [
                    {'kind': e[0], 'steps': e[3],
                     'age_s': round(now - e[4], 4)}
                    for e in list(self._decode_inflight)]
            except RuntimeError:
                # a harvest mutated the deque mid-snapshot (watchdog
                # thread races the worker); the slot map above stands
                ctx['decode_chain'] = None
        return ctx

    def stop(self):
        """Drain the queue and all in-flight dispatches, then join."""
        if self._closed:
            return
        self._closed = True
        self._batcher.close()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        else:
            self._drain_inline()
        if self._watchdog_probe is not None:
            _trace.watchdog.unregister(self._watchdog_probe,
                                       self._watchdog_age_fn)
            self._watchdog_probe = None
        _profiler.unregister_metrics_source(self._metrics_key,
                                            self._metrics_fn)

    close = stop

    @contextlib.contextmanager
    def paused(self):
        """Quiesce the engine: block inline submitters and the worker's
        dispatch cycles, drain every in-flight dispatch, and hold the
        engine idle for the duration of the with-block.  The HBM
        arbiter's eviction window — weights can move device<->host with
        no dispatch in flight.  submit() keeps queueing; queued requests
        simply wait out the pause."""
        with self._inline_lock:
            with self._cycle_lock:
                while self._inflight:
                    self._drain_one()
                if self._decode_cache is not None:
                    # the decode chain counts as in-flight dispatches
                    # too (ISSUE 9): an eviction moving slabs while a
                    # chained scan still references them would tear
                    # the carry — flush to a consistent boundary
                    self._decode_flush()
                yield self

    # ---- footprint / eviction (the ModelRegistry's arbiter hooks) ------

    def device_footprint(self):
        """Live HBM bytes attributable to this engine's model: the sum
        of device-resident (jax.Array) buffers held by its scope — the
        params the executor's cache_back staging pinned on device.
        (Executable HBM is XLA-internal; the arbiter carries it in the
        seed estimate.)  Sharded arrays report their GLOBAL byte size."""
        import jax
        total = 0
        for name in self._scope.local_var_names():
            v = self._scope.find_var(name).value()
            if isinstance(v, jax.Array):
                total += int(v.nbytes)
        return total

    def drop_executables(self, programs=None):
        """Drop every compiled executable for THIS engine's programs
        from its executor(s): the compile-cache entries (and their
        jitted multi/eval/decode scans) die, releasing XLA's
        device-side executable buffers.  Returns the number of cache
        entries dropped.  Only these programs' entries go — an executor
        shared with other models keeps theirs.  ``programs`` narrows
        the purge (the decode-cache eviction drops only the
        prefill/step executables); the default covers the engine's
        forward program plus the generation programs, if any."""
        if programs is None:
            programs = [self._program]
            if self.generation is not None:
                programs += [self.generation.prefill_program,
                             self.generation.step_program]
                if self.generation.chunk_program is not None:
                    programs.append(self.generation.chunk_program)
        pids = {id(p) for p in programs}
        dropped = 0
        for runner in (self._exe, self._pe, self._pe_prefill,
                       self._pe_step, self._pe_chunk):
            cache = getattr(runner, '_cache', None)
            if not cache:
                continue
            # the purge must exclude concurrent resolves: another model
            # sharing this executor may be between its cache get() and
            # move_to_end() on another thread (both executors expose
            # _cache_lock — Executor's from the concurrent-predictor
            # contract, ParallelExecutor's from the cost-registry work)
            lock = getattr(runner, '_cache_lock', None)
            with lock if lock is not None else contextlib.nullcontext():
                for k in [k for k in list(cache) if k[0] in pids]:
                    cache.pop(k, None)
                    dropped += 1
        return dropped

    def evict_to_host(self):
        """Demote the model to host memory under a paused() window:
        every device-resident scope buffer is copied back to a host
        ndarray (bitwise — dtype and values preserved, so the
        eviction->reload round trip is exact) and the program's
        executables are dropped.  Returns (bytes_moved,
        executables_dropped).  Reload is TRANSPARENT: the next dispatch
        re-stages host arrays through the normal cache_back path and
        recompiles on first use."""
        import jax
        with self.paused():
            moved = 0
            for name in self._scope.local_var_names():
                var = self._scope.find_var(name)
                v = var.value()
                if isinstance(v, jax.Array):
                    arr = np.asarray(v)
                    var.set_value(arr)
                    moved += int(arr.nbytes)
            dropped = self.drop_executables()
        return moved, dropped

    @staticmethod
    def _shard_nbytes(v):
        """ONE device's byte share of a live jax.Array — the shard
        shape when the sharding exposes it, the whole array otherwise
        (replicated arrays' shard IS the whole array).  The single
        per-device-bytes rule shared by ``hbm_footprint`` and
        ``table_live_bytes`` so arbiter billing and the footprint
        correction can never disagree."""
        try:
            shard = v.sharding.shard_shape(v.shape)
            return int(np.prod(shard)) * int(v.dtype.itemsize)
        except Exception:
            return int(v.nbytes)

    def hbm_footprint(self):
        """PER-DEVICE live HBM bytes attributable to this engine's
        scope (ISSUE 11): like ``device_footprint()`` but shard-aware —
        a mesh-row-sharded array (an 'mp' embedding table, a trainer
        scope's co-sharded moments) bills only ONE device's shard
        bytes, because the arbiter's budget is one chip's HBM.
        Replicated arrays (the plain dp case) are unchanged: their
        shard is the whole array, so this equals device_footprint()."""
        import jax
        total = 0
        for name in self._scope.local_var_names():
            v = self._scope.find_var(name).value()
            if isinstance(v, jax.Array):
                total += self._shard_nbytes(v)
        return total

    def table_live_bytes(self, var_name):
        """(global_bytes, per_device_bytes) of a mesh-row-sharded
        table's LIVE device array (ISSUE 11) — the arbiter bills the
        table's own account in per-device units (one chip holds only
        its shard), while ``device_footprint`` counts global bytes.
        (0, 0) when the var is host-resident or missing."""
        import jax
        var = self._scope.find_var(var_name)
        v = var.value() if var is not None else None
        if not isinstance(v, jax.Array):
            return 0, 0
        return int(v.nbytes), self._shard_nbytes(v)

    def embed_cache_of(self, var_name):
        """This engine's two-tier cache serving ``var_name`` (ISSUE
        12); KeyError when the var is not cached."""
        for cache in self._embed_caches:
            if cache.var == var_name:
                return cache
        raise KeyError('engine %r has no embed cache for %r'
                       % (self.name, var_name))

    def embed_cache_live_bytes(self, var_name):
        """Live DEVICE bytes of one cache's slabs (weight + optimizer
        accumulators) — the ``:embed-cache`` account's live
        correction; 0 while the slabs sit on host."""
        import jax
        cache = self.embed_cache_of(var_name)
        total = 0
        for name in cache.tables:
            var = self._scope.find_var(name)
            v = var.value() if var is not None else None
            if isinstance(v, jax.Array):
                total += self._shard_nbytes(v)
        return total

    def evict_embed_cache_to_host(self, var_name):
        """Demote ONE two-tier cache's slabs to host under a paused
        window (ISSUE 12; the arbiter's ``:embed-cache`` evict
        callback).  The flush inside first applies any staged exchange
        and writes every dirty row back to the host master — no torn
        slab even with a prefetch in flight — then the slabs demote
        bitwise and the next dispatch re-stages them transparently.
        Returns the bytes freed."""
        cache = self.embed_cache_of(var_name)
        with self.paused():
            return cache.evict_to_host()

    def evict_table_to_host(self, var_name):
        """Demote ONE mesh-row-sharded embedding table to host under a
        paused window (ISSUE 11; the arbiter's ``:embed-table`` evict
        callback): the shards copy back to a single bitwise host
        ndarray, and the next dispatch re-stages it sharded through the
        normal path.  Returns the PER-DEVICE bytes freed — the unit the
        table's account is charged in."""
        import jax
        with self.paused():
            var = self._scope.find_var(var_name)
            v = var.value() if var is not None else None
            if not isinstance(v, jax.Array):
                return 0
            _, per_dev = self.table_live_bytes(var_name)
            var.set_value(np.asarray(v))
        return per_dev

    @contextlib.contextmanager
    def _gated(self):
        gate = self._gate
        if gate is None:
            yield
        else:
            with gate:
                yield

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ---- request surface ----------------------------------------------

    def _service_estimate(self, req):
        """The shed horizon for ONE pending request (ISSUE 9): 3x the
        service-floor estimate of the request's OWN coalescing
        signature (min of that signature's recent dispatch walls,
        cost-seeded), falling back to the profile's global floor —
        and, before anything was ever profiled, to the engine-wide
        min-wall window (exactly the PR 8 global horizon, so the
        per-signature path only ever sharpens)."""
        est = self._profile.estimate(req.sig)
        if est is None:
            est = self._profile.floor()
        if est is None:
            est = (min(self._service_walls)
                   if self._service_walls else 0.0)
        return 3.0 * est

    def rate_stats(self):
        """Measured arrival vs drain rates (requests/s over the recent
        window; None while idle or single-sample) — the adaptive
        admission watermarks' inputs, surfaced for metrics()."""
        return {'arrival_req_s': self._arrivals.rate(),
                'drain_req_s': self._drains.rate()}

    def queue_depth(self):
        """Current micro-batch queue depth — the cheap load gauge
        (no metrics snapshot, no arbiter walk) the registry's
        status() and the fleet replica's per-response load report
        read (ISSUE 17)."""
        return self._batcher.depth()

    def _shed_request(self, req, where='queue'):
        """Resolve one past-deadline request as SHED (ISSUE 8): typed
        DeadlineExceededError, a 'shed' trace stage (the seconds the
        request sat before the scheduler dropped it), a flight-recorder
        record, and the metrics counter.  Called by the batcher at lot
        formation, by decode-slot admission, and by the decode lane's
        step-boundary deadline check."""
        if req.done():
            return
        now = time.time()
        late_ms = (round((now - req.deadline_t) * 1e3, 3)
                   if req.deadline_t is not None else None)
        if req.trace is not None:
            req.trace.add_stage('shed', now - req.enqueue_t)
            self._metrics.note_stages(req.trace.finalize(end=now))
        self._metrics.note_shed()
        _trace.flight_recorder.record(
            'serving_shed', engine=self.name, where=where,
            trace_id=req.trace_id, deadline_ms=req.deadline_ms,
            late_by_ms=late_ms)
        req.set_error(DeadlineExceededError(
            req.trace_id, req.deadline_ms, late_ms, where=where))

    def submit(self, feed, return_numpy=True, priority=0,
               deadline_ms=None):
        """Enqueue one request; returns an InferenceRequest future.
        When the engine is not start()ed, the dispatch runs inline on
        this thread (synchronous mode) and the future is already done.

        ``priority`` / ``deadline_ms`` (ISSUE 8): under the default
        'edf' scheduling, higher-priority requests form lots first,
        earliest deadline first within a class, and a request whose
        deadline passes while it waits is SHED — its future raises
        DeadlineExceededError and its trace carries a 'shed' stage —
        instead of being served late."""
        if self._closed:
            raise EngineClosedError('engine is closed')
        if not isinstance(feed, dict) or not feed:
            raise ValueError('feed must be a non-empty {name: data} dict')
        if self._feed_names is not None:
            missing = set(self._feed_names) - set(feed)
            extra = set(feed) - set(self._feed_names)
            if missing or extra:
                raise ValueError(
                    'feed names %s do not match the inference program '
                    '(missing %s, unexpected %s)' %
                    (sorted(feed), sorted(missing), sorted(extra)))
        # ONE trace id per request (ISSUE 6): adopt the ambient context
        # when a router (the ModelRegistry) attached one — its
        # arbitration seconds are already accumulated on it — else mint
        # a fresh one here.  The prepare half of 'pad' (LoD lowering,
        # trailing-rung padding) happens on THIS thread before the
        # request ever queues, so it is measured here; the lot-padding
        # half accrues between the worker's collect/lot marks.
        ctx = _trace.current() or _trace.TraceContext()
        t_prep = time.time()
        feed, rows, sig, trims = self._prepare_request(feed)
        ctx.add_stage('pad', time.time() - t_prep)
        req = InferenceRequest(feed, rows, sig, return_numpy=return_numpy,
                               trailing=trims, trace=ctx,
                               priority=priority, deadline_ms=deadline_ms)
        self._metrics.note_request(rows or 1)
        self._arrivals.note()
        ctx.mark('enqueue')
        self._batcher.submit(req)
        if self._thread is None:
            self._drain_inline()
        return req

    def infer(self, feed, return_numpy=True, timeout=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(feed, return_numpy=return_numpy).result(timeout)

    def submit_generate(self, feed, max_len=None, return_numpy=True,
                        priority=0, deadline_ms=None):
        """Enqueue one GENERATION request (ISSUE 7): ``feed`` is the
        prompt (the generation spec's prefill feeds, ONE sequence —
        rows must be 1), ``max_len`` the per-request step budget
        (capped by the spec's).  Returns a GenerationRequest future
        resolving to the generated token ids (greedy; EOS-terminated
        or cut at max_len) — token-identical to a per-request
        host-driven decode of the same prefill + step programs.

        The prompt coalesces into PREFILL lots with other generation
        requests (micro-batched, shape-bucketed, seq-len rung-
        quantized like any forward request); the prefilled state then
        ADMITS into a free decode slot at the next step boundary and
        rides the slot-batched in-jit decode scan — continuous
        batching, no drain barrier against requests already decoding.

        ``priority`` / ``deadline_ms`` ride the prefill lot like any
        forward request; the decode lane additionally checks the
        deadline at every step boundary (between K-step scans) — an
        expired generation releases its slot and sheds with whatever
        tokens it had, so dead decodes stop starving live ones."""
        from .decode import GenerationRequest
        if self.generation is None:
            raise RuntimeError(
                'submit_generate: this engine serves no generation '
                'model — construct it with generation=GenerationSpec(...)')
        if self._closed:
            raise EngineClosedError('engine is closed')
        spec = self.generation
        if not isinstance(feed, dict) or not feed:
            raise ValueError('feed must be a non-empty {name: data} dict')
        missing = set(spec.prefill_feeds) - set(feed)
        extra = set(feed) - set(spec.prefill_feeds)
        if missing or extra:
            raise ValueError(
                'submit_generate: feed names %s do not match the '
                'prefill program (missing %s, unexpected %s)'
                % (sorted(feed), sorted(missing), sorted(extra)))
        max_len = spec.max_len if max_len is None else int(max_len)
        if max_len < 1:
            raise ValueError('submit_generate: max_len must be >= 1')
        max_len = min(max_len, spec.max_len)
        # typed over-length reject (ISSUE 14 satellite): a prompt (or
        # prompt + generation budget) past the decode KV context would
        # otherwise surface as an opaque XLA shape/scatter error deep
        # inside prefill — or scatter silently off the slab mid-decode.
        # Measured HERE, on the raw feed, before any padding touches it.
        prompt_ids = prompt_len = None
        if spec.prompt_feed is not None and spec.prompt_feed in feed \
                and (self._chunking or spec.max_ctx is not None):
            # only when someone consumes it: the chunk lane slices it,
            # the max_ctx reject measures it — a plain monolithic
            # engine without a context bound must not pay the copy
            prompt_ids, prompt_len = spec.prompt_ids(feed)
        if self._chunking and prompt_len is not None and prompt_len < 1:
            # a zero-length prompt has no chunk to dispatch — without
            # this it would admit into a prefilling slot whose
            # finishing chunk never fires (the future would hang and
            # the slot leak)
            raise ValueError(
                'submit_generate: the prompt is empty — chunked '
                'prefill needs at least one token to consume')
        if spec.max_ctx is not None and prompt_len is not None:
            if prompt_len > spec.max_ctx:
                raise ValueError(
                    'submit_generate: prompt length %d exceeds the '
                    'decode context max_ctx=%d — the KV slab has no '
                    'row to hold token %d'
                    % (prompt_len, spec.max_ctx, spec.max_ctx))
            if prompt_len + max_len > spec.max_ctx:
                raise ValueError(
                    'submit_generate: prompt length %d + max_len %d '
                    'exceeds the decode context max_ctx=%d — generated '
                    'tokens would scatter off the KV slab; shorten the '
                    'prompt or lower max_len'
                    % (prompt_len, max_len, spec.max_ctx))
        if self._chunking and prompt_ids is None:
            raise ValueError(
                'submit_generate: chunked prefill needs the prompt '
                'feed %r in the request' % (spec.prompt_feed, ))
        ctx = _trace.current() or _trace.TraceContext()
        if self._chunking:
            # chunked prefill never forms a prefill lot, so the
            # rung-padding pass (_prepare_request) would be a wasted
            # full-prompt copy on the caller thread — long prompts are
            # exactly this lane's workload.  Only the one-sequence
            # check remains; the request carries no feed (the chunk
            # lane reads prompt_tokens) and a constant coalescing sig
            # (chunk-pending requests never share an executable).
            rows = self._chunk_prompt_rows(feed[spec.prompt_feed])
            if rows != 1:
                raise ValueError(
                    'submit_generate: the prompt must be ONE sequence '
                    '(got %r rows) — submit one request per sequence '
                    'so each occupies one decode slot' % (rows, ))
            feed, sig = None, ('gen-chunk', )
        else:
            t_prep = time.time()
            feed, rows, sig, _trims = self._prepare_request(feed)
            ctx.add_stage('pad', time.time() - t_prep)
            if rows is None:
                # the unbatchable path (nested LoD, or an LoD prompt
                # with trailing bucketing disabled) has no coalescible
                # prefill signature — say WHY instead of 'got None
                # rows'
                raise ValueError(
                    'submit_generate: this prompt cannot ride the '
                    'batched prefill path — nested (2-level) LoD '
                    'prompts are unsupported, and LoD prompts need '
                    'trailing bucketing (drop '
                    'ServingConfig(trailing_buckets=False))')
            if rows != 1:
                raise ValueError(
                    'submit_generate: the prompt must be ONE sequence '
                    '(got %r rows) — submit one request per sequence '
                    'so each occupies one decode slot' % (rows, ))
            # the 'gen' sig prefix keeps prefill lots out of forward
            # lots even when the raw feed signatures collide
            sig = ('gen', ) + tuple(sig)
        req = GenerationRequest(feed, 1, sig, max_len,
                                return_numpy=return_numpy, trace=ctx,
                                priority=priority,
                                deadline_ms=deadline_ms)
        if self._chunking:
            req.prompt_tokens = prompt_ids
            req.prompt_len = prompt_len
        self._metrics.note_generate()
        self._arrivals.note()
        ctx.mark('enqueue')
        self._batcher.submit(req)
        if self._thread is None:
            self._drain_inline()
        return req

    @staticmethod
    def _chunk_prompt_rows(v):
        """How many sequences the prompt feed carries (the chunked
        lane's one-sequence check, without the monolithic path's
        rung-padding pass): LoD prompts count their top-level
        sequences (nested LoD rejected — flattening it into chunk
        blocks would silently concatenate sequences), dense prompts
        their leading dim."""
        if isinstance(v, core.LoDTensor) and v.lod():
            if len(v.lod()) >= 2:
                raise ValueError(
                    'submit_generate: nested (2-level) LoD prompts '
                    'are unsupported under chunked prefill')
            return max(len(v.lod()[-1]) - 1, 0)
        shape = np.shape(v.numpy() if isinstance(v, core.LoDTensor)
                         else v)
        return int(shape[0]) if shape else 0

    def generate(self, feed, max_len=None, timeout=None):
        """Synchronous convenience: submit_generate + wait."""
        return self.submit_generate(feed, max_len=max_len).result(timeout)

    def metrics(self):
        """Engine snapshot + bucket report + the executor's own XLA
        compile counter (the ground truth the bucket policy bounds)."""
        snap = self._metrics.snapshot(
            queue_depth=self._batcher.depth(),
            queue_age=self._batcher.age_stats())
        snap['buckets'] = self.buckets.report()
        snap['trailing_buckets'] = (self.trailing.report()
                                    if self.trailing is not None else None)
        snap['executor_compile_count'] = (
            self._pe.compile_count if self._pe is not None
            else self._exe.compile_count)
        if self._pe is not None and self._pe_step is not None:
            # sharded generation compiles its prefill/step (and chunk)
            # executables on their own PEs — fold them into the
            # ground-truth count
            snap['executor_compile_count'] += (
                self._pe_prefill.compile_count +
                self._pe_step.compile_count)
            if self._pe_chunk is not None:
                snap['executor_compile_count'] += \
                    self._pe_chunk.compile_count
        snap['inflight'] = len(self._inflight)
        snap['decode'] = (self._metrics.decode_snapshot(
            active_slots=self._decode_cache.active_slots(),
            free_slots=self._decode_cache.free_slots(),
            pending=len(self._gen_ready) + len(self._chunk_pending),
            inflight_scans=len(self._decode_inflight))
            if self._decode_cache is not None else None)
        # the two-tier embedding cache's counters (ISSUE 12):
        # hit/miss/stall/writeback per cached table
        snap['embed_cache'] = ({c.var: c.metrics()
                                for c in self._embed_caches}
                               if self._embed_caches else None)
        # per-signature service profile + the rate pair the adaptive
        # watermarks read (ISSUE 9)
        snap['service_profile'] = self._profile.snapshot()
        rates = self.rate_stats()
        snap['arrival_req_s'] = (round(rates['arrival_req_s'], 3)
                                 if rates['arrival_req_s'] else None)
        snap['drain_req_s'] = (round(rates['drain_req_s'], 3)
                               if rates['drain_req_s'] else None)
        return snap

    # ---- request -> lot -----------------------------------------------

    def _prepare_request(self, feed):
        """(feed, rows, coalescing signature, trailing trim map) for a
        request.  With trailing bucketing on, single-level LoD feeds
        lower to padded [B, T, ...] + @SEQLEN here (the executor's own
        lowering, already rung-quantized) and PaddedSequence / dense
        ladder feeds zero-pad their trailing axes up to the covering
        TrailingDimBuckets rung — so mixed-length requests in one rung
        share a signature and coalesce.  Unbatchable feeds (host-op
        programs, scalars, NESTED LoD — whose outer @ROWS level is not
        row-aligned for per-request slicing — or any sequence feed with
        trailing bucketing disabled) come back as (feed, None, unique,
        None): single-request lots with no padding, the old path."""
        if self._eager:
            return feed, None, object(), None
        seq_like = False
        for v in feed.values():
            if isinstance(v, core.PaddedSequence):
                if self.trailing is None or v.rows is not None:
                    return feed, None, object(), None
                seq_like = True
            elif isinstance(v, core.LoDTensor) and v.lod():
                if self.trailing is None or len(v.lod()) >= 2:
                    return feed, None, object(), None
                seq_like = True
        items = prepare_feed_arrays(feed) if seq_like else dict(feed)
        # validate BEFORE bucketing: _bucket_trailing pads in place and
        # records padding-waste / rung-hit metrics — a request rejected
        # here (or routed to the unbatchable path) must leave no trace
        # in the trailing accounting
        leads = {}
        for name, v in sorted(items.items()):
            lead = _lead(v)
            if lead is None:
                return feed, None, object(), None
            if lead == 0:
                raise ValueError(
                    'feed %r has 0 rows — an empty request has no '
                    'result to serve' % name)
            leads[name] = lead
        if len(set(leads.values())) > 1:
            raise ValueError(
                'feeds disagree on the leading (batch) dim: %s — every '
                'input of one request must carry the same number of '
                'rows' % ({n: d for n, d in sorted(leads.items())}, ))
        trims = self._bucket_trailing(items) \
            if self.trailing is not None else None
        sig = []
        for name, v in sorted(items.items()):
            arr_like = v.numpy() if isinstance(v, core.LoDTensor) else v
            shape = tuple(np.shape(arr_like))
            dtype = getattr(arr_like, 'dtype', None)
            if dtype is None:
                dtype = np.asarray(arr_like).dtype
            sig.append((name, shape[1:], str(dtype)))
        return (items, int(next(iter(leads.values()))), tuple(sig),
                trims)

    def _bucket_trailing(self, items):
        """Quantize ``items``' variable trailing dims onto the
        TrailingDimBuckets ladder IN PLACE (zero-fill, the same pad
        _lod_to_padded applies): axis 1 of every feed carrying a
        @SEQLEN companion rides the shared seq-len policy; feeds named
        in ``trailing_ladders`` pad their configured axes.  Returns the
        axis-1 trim map {padded_extent: real_extent} for the deliver
        path (a padded extent claimed by two feeds with DIFFERENT real
        extents — including a feed sitting exactly ON the rung, or a
        NON-bucketed feed's static axis-1 extent, or a FETCH target's
        static axis-1 width, coinciding with it — is ambiguous and
        dropped: such fetches deliver at the rung, documented in
        _drain_one)."""
        claims = {}  # rung -> set of real axis-1 extents claiming it
        # extents a trim must never match: the static axis 1 of feeds
        # NOT bucketed on axis 1 (collected below — including feeds
        # whose ladders live on axes >= 2) and the fetch targets'
        # static axis 1 (a [B, 16] softmax under a 16 rung is the
        # fetch's OWN width, not rung padding)
        static_ax1 = set(self._fetch_static_ax1)
        plan = []  # (name, axes, explicit, shape) — validated upfront
        for name in list(items):
            if name.endswith((SEQLEN_SUFFIX, ROWS_SUFFIX)) or \
                    name == SAMPLE_MASK_NAME:
                continue
            explicit = set(self.trailing.ladder_axes(name))
            axes = set(explicit)
            if (name + SEQLEN_SUFFIX) in items:
                axes.add(1)
            v = items[name]
            shape = tuple(v.shape() if isinstance(v, core.LoDTensor)
                          else np.shape(v))
            for ax in sorted(explicit):
                if ax >= len(shape):
                    # a configured ladder axis the data doesn't have
                    # would otherwise be skipped silently — that feed
                    # would never coalesce and nothing would say why
                    # (the constructor already rejects axis < 1 for
                    # the same reason).  Raised HERE, before any feed
                    # touches bucket hits or padding metrics, so the
                    # rejected request leaves no trailing trace.
                    raise ValueError(
                        'trailing ladder for feed %r names axis '
                        '%d, but the request has only %d dims — '
                        'fix trailing_ladders' % (name, ax,
                                                  len(shape)))
            for ax in sorted(axes):
                if 1 <= ax < len(shape) and int(shape[ax]) < 1:
                    # bucket_for would raise the same complaint, but
                    # mid-loop — after OTHER feeds already recorded
                    # rung hits and padding cells
                    raise ValueError(
                        'feed %r has zero width on bucketed trailing '
                        'axis %d — an empty extent has nothing to '
                        'serve' % (name, ax))
            if 1 not in axes and len(shape) >= 2:
                static_ax1.add(int(shape[1]))
            if axes:
                plan.append((name, axes, explicit, shape))
        for name, axes, explicit, shape in plan:
            v = items[name]
            rows = max(int(shape[0]), 1) if shape else 1
            pads, prod_real, prod_rung = [], 1, 1
            seq_lens_sum, bucketed = None, False
            for ax in sorted(axes):
                if ax >= len(shape) or ax < 1:
                    continue
                real = int(shape[ax])
                rung = self.trailing.bucket_for(name, ax, real)
                bucketed = True
                if ax == 1 and (name + SEQLEN_SUFFIX) in items:
                    # the TRUE occupancy of a seq feed's time axis is
                    # its lengths sum — the rung pad a prepared LoD
                    # feed already carries (inside _lod_to_padded)
                    # must count as waste too, not just the extra pad
                    # this pass adds
                    seq_lens_sum = max(int(np.sum(np.asarray(
                        items[name + SEQLEN_SUFFIX]))), 0)
                    prod_rung *= rung
                else:
                    prod_real *= real
                    prod_rung *= rung
                if ax == 1:
                    claims.setdefault(rung, set()).add(real)
                if rung != real:
                    pads.append((ax, rung - real))
            if pads:
                arr = np.asarray(v.numpy() if isinstance(v, core.LoDTensor)
                                 else v)
                width = [(0, 0)] * arr.ndim
                for ax, p in pads:
                    width[ax] = (0, p)
                items[name] = np.pad(arr, width)
            if bucketed:
                base = seq_lens_sum if seq_lens_sum is not None else rows
                self._metrics.note_trailing(base * prod_real,
                                            rows * prod_rung)
        # order-independent ambiguity: a rung claimed by two feeds with
        # different real extents (even one sitting exactly ON it), or
        # coinciding with a NON-bucketed feed's static axis-1 extent (a
        # fetch of that width could mirror EITHER axis), has no single
        # trim answer
        trims = {rung: reals.pop() for rung, reals in claims.items()
                 if len(reals) == 1 and rung not in reals
                 and rung not in static_ax1}
        return trims or None

    def _make_lot(self, requests):
        now = time.time()
        for r in requests:
            if r.trace is not None:
                r.trace.mark('collect', now)
        if _profiler.is_profiler_enabled() or _trace.spans_enabled():
            # a tracing()-only window gets these spans too — the
            # documented contract is that every profiler event mirrors
            # into the span log, profiler running or not
            for r in requests:
                _profiler.record_event(self._spans + 'queue_wait',
                                       now - r.enqueue_t,
                                       start=r.enqueue_t)
        head = requests[0]
        if head.rows is None:
            # unbatchable (LoD/scalar feeds, or an eager host-op
            # program): its own lot, no padding — still a lot in the
            # metrics (real == bucket rows, so the fill ratio is
            # unaffected) or capacity math reads 'served nothing'
            self._metrics.note_lot(1, 1, deadline_flush=False)
            if head.trace is not None:
                head.trace.mark('lot')
            return _Lot(requests, dict(head.feed), None, None,
                        ('nobatch', id(head)), kind=head.kind)
        rows = sum(r.rows for r in requests)
        bucket = self.buckets.bucket_for(rows)
        names = set(head.feed)
        if len(requests) == 1:
            # pass values through untouched — pad_ragged_batch already
            # leaves device-staged arrays on device when nothing pads
            feed = dict(head.feed)
        else:
            feed = {n: np.concatenate([
                np.asarray(r.feed[n].numpy()
                           if isinstance(r.feed[n], core.LoDTensor)
                           else r.feed[n]) for r in requests])
                for n in names}
        # force_mask keeps ONE signature per bucket: a full lot and a
        # padded lot compile to the same executable (mask all-ones vs
        # ragged) instead of doubling the compile set
        feed, real, target = pad_ragged_batch(
            feed, 1, target=bucket, force_mask=True, batch_names=names)
        deadline_flush = rows < self.config.max_batch_size
        self._metrics.note_lot(real, target, deadline_flush)
        t_lot = time.time()
        for r in requests:
            if r.trace is not None:
                r.trace.mark('lot', t_lot)
        # kind is part of the block sig: a prefill lot must never share
        # a scan block with a forward lot of a coinciding signature
        return _Lot(requests, feed, real, target,
                    (head.kind, target, feed_signature(feed)),
                    kind=head.kind)

    # ---- dispatch / deliver -------------------------------------------

    def _dispatch(self, lots):
        """ONE run_eval_multi dispatch over K same-bucket lots; tracks
        it in the in-flight pipeline (no host sync here).  Host-op
        (eager) programs run one exe.run per lot instead — the scan
        cannot contain them."""
        if self._eager:
            return self._dispatch_eager(lots)
        t0 = time.time()
        prefill = lots[0].kind == 'generate'
        if prefill:
            # a prefill lot runs the generation spec's PREFILL program,
            # fetching the initial decoder state instead of the
            # engine's fetch list — same scan machinery, different
            # executable set
            program = self.generation.prefill_program
            fetch_list = self.generation.prefill_fetches
            runner = self._pe_prefill if self._pe is not None \
                else self._exe
            self._metrics.note_prefill_lot()
            # the stall gauge's "prefill in flight" marker (ISSUE 14):
            # this lot's compute lands between decode scans on device
            self._prefill_since_harvest = True
        else:
            program = self._program
            fetch_list = self._fetch_list
            runner = self._pe if self._pe is not None else self._exe
        before = runner.compile_count
        trace_ids = [r.trace_id for lot in lots for r in lot.requests]
        # the flight recorder's lot record goes in BEFORE the dispatch:
        # when the dispatch itself wedges or errors, the dump must show
        # what was being dispatched, not just what already succeeded
        _trace.flight_recorder.record(
            'serving_dispatch', engine=self.name, lots=len(lots),
            lot_kind=lots[0].kind,
            bucket=lots[0].bucket, sig=repr(lots[0].sig)[:128],
            rows=[lot.real for lot in lots], trace_ids=trace_ids)
        feed_list = [l.feed for l in lots]
        try:
            if self._embed_caches and not prefill:
                # inference lookups ride the SAME hot-row slab (ISSUE
                # 12): remap the lots' id feeds to slots (copies — an
                # errored lot must keep its raw ids) and land the
                # exchange before the dispatch that reads the slab.
                # train=False: serving never dirties rows, evictions
                # are free.  A staging fault (capacity, out-of-range
                # ids) errors the lot's futures, never the worker.
                feed_list = [dict(f) for f in feed_list]
                for cache in self._embed_caches:
                    cache.apply(cache.stage_feed_list(
                        feed_list, train=False, steps=len(feed_list)))
            with self._gated():
                if self._pe is not None:
                    stacked, reals, target, compiled, k = \
                        runner._dispatch_eval_multi(
                            fetch_list,
                            feed_list=feed_list)
                else:
                    stacked, reals, target, compiled, k = \
                        self._exe._dispatch_eval_multi(
                            program,
                            feed_list=feed_list,
                            fetch_list=fetch_list, scope=self._scope)
        except Exception as exc:
            self._metrics.note_error()
            _trace.flight_recorder.dump(
                'worker_error:%s' % self.name, error=repr(exc),
                trace_ids=trace_ids)
            for lot in lots:
                for req in lot.requests:
                    req.set_error(exc)
            return
        self._metrics.note_dispatch(k, runner.compile_count - before)
        t_disp = time.time()
        for lot in lots:
            for req in lot.requests:
                if req.trace is not None:
                    req.trace.mark('dispatch', t_disp)
        # snapshot the per-dispatch cost entry NOW: a later dispatch on
        # the same compiled block overwrites last_eval_cost before this
        # one drains (FIFO drain, pipeline_depth > 1 in flight)
        cost = getattr(compiled, 'last_eval_cost', None)
        self._inflight.append((stacked, lots, compiled, t0, t_disp, cost))

    def _dispatch_eager(self, lots):
        """Per-lot exe.run for host-op programs (save/print/readers):
        identical semantics to the pre-engine Inferencer, delivered
        synchronously — nothing to pipeline when every step round-trips
        the host anyway."""
        for lot in lots:
            t0 = time.time()
            req = lot.requests[0]  # eager lots are single-request
            before = self._exe.compile_count
            if req.trace is not None:
                req.trace.mark('dispatch', t0)
            _trace.flight_recorder.record(
                'serving_dispatch', engine=self.name, lots=1, eager=True,
                trace_ids=[req.trace_id])
            try:
                with self._gated():
                    outs = self._exe.run(self._program, feed=lot.feed,
                                         fetch_list=self._fetch_list,
                                         scope=self._scope,
                                         return_numpy=req.return_numpy)
            except Exception as exc:
                self._metrics.note_error()
                _trace.flight_recorder.dump(
                    'worker_error:%s' % self.name, error=repr(exc),
                    trace_ids=[req.trace_id])
                req.set_error(exc)
                continue
            self._metrics.note_dispatch(
                1, self._exe.compile_count - before)
            if req.trace is not None:
                # eager runs are synchronous: the device stage IS the
                # exe.run window, and delivery follows immediately
                req.trace.mark('sync')
                self._metrics.note_stages(req.trace.finalize())
            req.set_result(outs)
            if req.latency_s is not None:
                self._metrics.note_latency(req.latency_s)
            if _profiler.is_profiler_enabled() or _trace.spans_enabled():
                _profiler.record_event(self._spans + 'dispatch[eager]',
                                       time.time() - t0, start=t0)

    def _drain_one(self):
        """Deliver the OLDEST in-flight dispatch: host sync, trim each
        lot to its real rows, slice per request, resolve futures."""
        stacked, lots, compiled, t0, t_disp, cost = \
            self._inflight.popleft()
        try:
            arrays = [np.asarray(a) for a in stacked]  # the sync point
        except Exception as exc:
            self._metrics.note_error()
            _trace.flight_recorder.dump(
                'worker_error:%s' % self.name, error=repr(exc),
                trace_ids=[r.trace_id for lot in lots
                           for r in lot.requests])
            for lot in lots:
                for req in lot.requests:
                    req.set_error(exc)
            return
        t_sync = time.time()
        for lot in lots:
            for req in lot.requests:
                if req.trace is not None:
                    req.trace.mark('sync', t_sync)
        # achieved MFU: XLA's own FLOPs for the drained executable over
        # the wall window the device could have spent on THIS dispatch.
        # With pipeline_depth > 1 dispatch N+1 is issued while N still
        # executes, so [t_disp, t_sync] windows of consecutive drains
        # overlap — summing them double-counts wall time and halves the
        # reported rate under load.  Clip each window to start no
        # earlier than the previous drain's sync.  A backend whose
        # analysis yields no 'flops' must not grow the seconds
        # denominator either, or mixed entries deflate device_flops_per_s
        dev_start = max(t_disp, self._last_sync_t)
        if cost is not None and cost.get('flops') and t_sync > dev_start:
            self._metrics.note_device(cost['flops'], t_sync - dev_start)
        # service-time window (ISSUE 8): one dispatch's RAW issue->sync
        # span feeds the batcher's shed horizon — a deadlined request
        # that cannot be served within ~2x the recent MINIMUM span
        # sheds instead of burning the dispatch it would miss anyway.
        # Deliberately NOT the clipped device window above: under
        # pipeline_depth >= 2 the raw span includes the wait behind
        # earlier in-flight dispatches, and that wait IS part of the
        # time a newly formed lot takes to deliver — estimating from
        # the clipped window makes EDF pick requests it then serves
        # just past their deadline (measured: the slo gate's edf_late
        # jumps ~10x).  The min-of-8 still discards compile outliers.
        wall = max(t_sync - t0, 0.0)
        self._service_walls.append(wall)
        # per-signature profile (ISSUE 9): the same raw wall, keyed by
        # each lot's coalescing signature (every request in a lot
        # shares it — the batcher's coalescing rule), with a cost-
        # registry seed the first time a signature drains so the
        # min-window never bottoms out at a compile-polluted cold
        # wall.  ONE observation per distinct signature per dispatch:
        # the lots of a multi-lot scan block share their signature
        # (_collect_block's rule), and K duplicate appends would
        # shrink the min-window to ~8/K distinct dispatches of history
        for key in {lot.requests[0].sig for lot in lots}:
            if cost is not None and cost.get('flops'):
                rate = self._metrics.device_rate()
                if rate:
                    self._profile.seed(key, cost['flops'] / rate)
            self._profile.observe(key, wall)
        self._last_sync_t = t_sync
        led = fetch_batch_led(compiled, len(arrays))
        if not all(led) and not self._warned_unsliced and \
                any(len(lot.requests) > 1 for lot in lots):
            # a batch-REDUCED fetch (a mean/accuracy scalar) from a
            # coalesced lot is computed over EVERY rider's rows — there
            # is no per-request value to slice out, so each caller gets
            # the whole-lot number.  Say so once instead of silently
            # breaking per-request parity for such fetches.
            self._warned_unsliced = True
            import warnings
            warnings.warn(
                'serving engine %s: fetches %s are not per-row '
                '(batch-led) — coalesced requests receive the value '
                'computed over the WHOLE micro-batch, not their own '
                'rows.  Fetch per-row outputs, or serve such programs '
                'with max_batch_size=1.' %
                (self.name,
                 [n for n, is_led in zip(
                     getattr(compiled, 'fetch_names',
                             range(len(led))), led) if not is_led]))
        for j, lot in enumerate(lots):
            offset = 0
            for req in lot.requests:
                res = []
                for a, is_led in zip(arrays, led):
                    step = a[j]
                    if lot.real is not None and is_led \
                            and np.ndim(step) >= 1 \
                            and np.shape(step)[0] == lot.bucket:
                        step = step[offset:offset + req.rows]
                        if req.trailing is not None \
                                and np.ndim(step) >= 2:
                            # trailing-dim trim (ISSUE 5): a per-row
                            # fetch mirroring a rung-padded input axis
                            # (axis 1 == a padded extent this request
                            # recorded) trims back to the request's
                            # REAL extent — so a PaddedSequence/dense-
                            # ladder caller gets fetches shaped like
                            # its own input, not like the rung.
                            # (Extent-match is a heuristic like the
                            # batch one above; ambiguous extents were
                            # dropped at request build and deliver at
                            # the rung.  Residual: STATIC widths —
                            # feeds' and fetches' — void their rungs
                            # upfront, but a fetch whose axis 1 is
                            # dynamic AND whose runtime width lands on
                            # a claimed rung without mirroring the
                            # padded axis is indistinguishable here;
                            # disable trailing_buckets for such
                            # programs.)
                            real = req.trailing.get(np.shape(step)[1])
                            if real is not None:
                                step = step[:, :real]
                    if not req.return_numpy and req.kind != 'generate':
                        # a generate request's prefill slices feed slot
                        # admission — they stay raw arrays regardless
                        step = core.LoDTensor(np.asarray(step))
                    res.append(step)
                offset += req.rows or 0
                if req.kind == 'generate':
                    # a PREFILL result: the per-request state slices
                    # queue for slot admission at the next decode step
                    # boundary (continuous batching — no drain barrier
                    # against slots already decoding); the future
                    # resolves when the decode lane finishes the
                    # request
                    self._gen_ready.append((req, res))
                    continue
                if req.trace is not None:
                    # finalize BEFORE resolving the future: a caller
                    # woken by result() must see a complete breakdown
                    self._metrics.note_stages(req.trace.finalize())
                    _trace.record_span(
                        self._spans + 'request', req.trace.t0,
                        req.trace.e2e_s, trace_id=req.trace_id)
                req.set_result(res)
                self._drains.note()
                if req.latency_s is not None:
                    self._metrics.note_latency(req.latency_s)
        if _profiler.is_profiler_enabled() or _trace.spans_enabled():
            _profiler.record_event(
                self._spans + 'dispatch[x%d]' % len(lots),
                time.time() - t0, start=t0)

    # ---- decode lane (ISSUE 7) ----------------------------------------

    def _admit_ready(self):
        """Admit prefilled generation requests into free decode slots
        (step-boundary admission — the host half of continuous
        batching).  Returns how many were admitted."""
        admitted = 0
        while self._gen_ready and self._decode_cache.free_slots():
            req, values = self._gen_ready.popleft()
            if req.done():
                continue  # errored upstream; nothing to decode
            if self.config.scheduling == 'edf' and \
                    req.deadline_t is not None and \
                    time.time() > req.deadline_t:
                # prefilled but dead on arrival at the slot: shedding
                # here frees the slot-steps its whole generation would
                # have wasted.  'fifo' admits it anyway — that mode's
                # contract is serve-everything-late, nothing shed.
                self._shed_request(req, where='admit')
                continue
            try:
                self._decode_cache.admit(req, values)
            except Exception as exc:
                self._metrics.note_error()
                req.set_error(exc)
                continue
            if req.trace is not None:
                req.trace.mark('admit')
            admitted += 1
        return admitted

    def _decode_dispatch(self):
        """Enqueue ONE K-step decode scan against the cache's CURRENT
        carry — which, mid-chain, is the previous scan's device-
        resident output (donated in place on device): scan N+1 chains
        onto scan N with no token block materializing on host (ISSUE
        9).  The async token/alive outputs go on the in-flight chain
        for a later harvest.  Returns True when a scan dispatched."""
        cache = self._decode_cache
        k = self.config.decode_steps
        snap = cache.snapshot()
        # slot-map snapshot BEFORE the dispatch: a wedged or erroring
        # decode scan must leave the occupancy picture in the ring —
        # chain_depth records how many scans were already in flight
        _trace.flight_recorder.record(
            'decode_lot', engine=self.name, steps=k,
            chain_depth=len(self._decode_inflight), slot_map=snap)
        try:
            with self._gated():
                if self._pe is not None:
                    carry, toks, alive_in, _ = \
                        self._pe_step._dispatch_decode_multi(
                            carry=cache.carry(), steps=k,
                            decode=self._gen_decode_arg)
                else:
                    carry, toks, alive_in, _ = \
                        self._exe._dispatch_decode_multi(
                            self.generation.step_program,
                            carry=cache.carry(), steps=k,
                            decode=self._gen_decode_arg,
                            scope=self._scope)
        except Exception as exc:
            self._decode_fail(exc, snap)
            return False
        # the cache's carry is now the NEW scan's async output: the
        # next dispatch chains onto it without waiting for this one
        cache.set_carry(carry)
        # capture the slot->request map AT DISPATCH: a slot released
        # (and re-admitted) at a later flush must not receive this
        # scan's tokens — the done() guard at harvest closes the loop
        reqs = [cache.request_at(s) for s in range(cache.slots)]
        self._decode_inflight.append(
            ('decode', toks, alive_in, k, time.time(), reqs, snap))
        return True

    # ---- chunked prefill (ISSUE 14) -----------------------------------

    def _admit_chunk_pending(self):
        """Admit pending chunked-prefill prompts into free slots in the
        PREFILLING phase (chain-flush points, like _admit_ready).
        Returns how many were admitted."""
        admitted = 0
        while self._chunk_pending and self._decode_cache.free_slots():
            req = self._chunk_pending.popleft()
            if req.done():
                continue
            if self.config.scheduling == 'edf' and \
                    req.deadline_t is not None and \
                    time.time() > req.deadline_t:
                self._shed_request(req, where='admit')
                continue
            self._decode_cache.admit_prefilling(req)
            admitted += 1
        return admitted

    def _chunk_estimate(self):
        """The expected wall of one chunk dispatch: the profile's
        estimate for the chunk signature (cost-seeded, min-of-recent-
        walls), falling back to the measured chunk-wall floor."""
        est = self._profile.estimate(('chunk', self.config.prefill_chunk))
        if est is None:
            est = min(self._chunk_walls) if self._chunk_walls else 0.0
        return est

    def _chunk_should_dispatch(self):
        """At most ONE prefill chunk rides each worker cycle (the call
        site enforces the once-per-cycle half) — and only when it fits
        the decode lane's deadline headroom: under EDF, if some ACTIVE
        decoding request's deadline lands before the next step boundary
        plus a chunk wall, the chunk waits a cycle instead of stalling
        the token that would make that deadline (decode priority — the
        whole point of chunking).  Without imminent deadlines the chunk
        always rides."""
        if not self._chunking:
            return False
        cache = self._decode_cache
        if not any(cur < req.prompt_len
                   for _, req, cur in cache.prefilling_items()
                   if req is not None):
            return False
        if self.config.scheduling == 'edf':
            deadlines = [
                req.deadline_t for req in cache.active_requests()
                if not req.prefilling and req.deadline_t is not None
                and not req.done()]
            if deadlines:
                est_scan = (min(self._decode_walls)
                            if self._decode_walls else 0.0)
                if time.time() + est_scan + self._chunk_estimate() > \
                        min(deadlines):
                    return False
        return True

    def _chunk_dispatch(self):
        """Dispatch ONE C-token chunk advancing EVERY prefilling slot
        (batched, masked — the chunk sibling of _decode_dispatch),
        chained on the cache's current carry.  Slots whose prompt ends
        inside this block transition to decoding ON DEVICE (the kernel
        flips token/alive/budget), so the next decode scan picks them
        up at a step boundary; their cursors/phases mirror host-side
        deterministically.  Returns True when a chunk dispatched."""
        cache = self._decode_cache
        spec = self.generation
        c = self.config.prefill_chunk
        s = cache.slots
        work = [(idx, req, cur) for idx, req, cur
                in cache.prefilling_items()
                if req is not None and cur < req.prompt_len]
        if not work:
            return False
        blk = np.zeros((s, c, 1), np.int64)
        lens = np.zeros((s, ), np.int32)
        active = np.zeros((s, ), bool)
        fin = np.zeros((s, ), bool)
        budget = np.zeros((s, ), np.int32)
        for idx, req, cur in work:
            n = min(c, req.prompt_len - cur)
            blk[idx, :n, 0] = req.prompt_tokens[cur:cur + n]
            lens[idx] = n
            active[idx] = True
            if cur + n >= req.prompt_len:
                fin[idx] = True
                budget[idx] = req.max_len
        feed = {spec.chunk_token: blk,
                spec.chunk_token + SEQLEN_SUFFIX: lens}
        if spec.chunk_len is not None:
            feed[spec.chunk_len] = lens.astype(np.float32)[:, None]
        aux = {'active': active, 'finish': fin, 'budget': budget}
        snap = cache.snapshot()
        _trace.flight_recorder.record(
            'chunk_lot', engine=self.name, width=int(c),
            prefilling=len(work), finishing=int(fin.sum()),
            chain_depth=len(self._decode_inflight), slot_map=snap)
        try:
            with self._gated():
                if self._pe_chunk is not None:
                    carry, ok, _ = self._pe_chunk._dispatch_chunk_prefill(
                        feed=feed, carry=cache.carry(), aux=aux,
                        chunk=self._gen_chunk_arg)
                else:
                    carry, ok, _ = self._exe._dispatch_chunk_prefill(
                        spec.chunk_program, feed=feed,
                        carry=cache.carry(), aux=aux,
                        chunk=self._gen_chunk_arg, scope=self._scope)
        except Exception as exc:
            self._decode_fail(exc, snap)
            return False
        cache.set_carry(carry)
        self._metrics.note_chunk_dispatch(
            sum(int(lens[idx]) for idx, _, _ in work))
        self._prefill_since_harvest = True
        t_disp = time.time()
        for idx, req, cur in work:
            cache.advance_prefill(idx, int(lens[idx]))
            if fin[idx]:
                cache.finish_prefill(idx)
                if req.trace is not None:
                    # decode begins at this dispatch: the 'prefill'
                    # trace stage (collect -> admit) ends here
                    req.trace.mark('admit', t_disp)
        self._decode_inflight.append(
            ('chunk', ok, None, int(c), t_disp, None, snap))
        return True

    def _decode_harvest_one(self):
        """Harvest the OLDEST in-flight decode-lane dispatch (ISSUE 9 —
        the host half the per-scan-sync lane paid BETWEEN scans now
        runs while the next scan computes).  A 'chunk' entry (ISSUE
        14) syncs only its small completion marker: the chunk wall
        feeds the decode-priority budget (and a deferred device error
        poisons the chain exactly like a scan's).  A 'decode' entry
        syncs its token block, replays the scan's stop-condition
        masking host-side (EOS emitted / budget exhausted — the exact
        in-scan rule, so the host mirror never drifts from the device
        carry), delivers every request the scan finished, and releases
        their slots.  Returns True unless the chain was poisoned."""
        kind, payload, alive_dev, k, t_disp, reqs, snap = \
            self._decode_inflight.popleft()
        # a harvest with NOTHING in flight behind it is a device-idling
        # HOST SYNC — the quantity the chained lane minimizes (the
        # per-scan-sync lane pays one per scan).  Judged at pop,
        # counted only on a SUCCESSFUL sync: a poisoned harvest must
        # not inflate the harvests/host_syncs counters the
        # decode_overlap gate and bench/load_gen reports are built on
        blocking = not self._decode_inflight
        cache = self._decode_cache
        if kind == 'chunk':
            try:
                np.asarray(payload)          # the sync point
            except Exception as exc:
                self._decode_fail(exc, snap)
                return False
            wall = max(time.time() - t_disp, 0.0)
            self._chunk_walls.append(wall)
            self._profile.observe(('chunk', self.config.prefill_chunk),
                                  wall)
            # a chunk harvest is a real host sync too: the ISSUE 9
            # ledger must see a chunk lane degraded to per-dispatch
            # sync (blocking with nothing behind it), or the gauges
            # built to catch that would stay flat
            self._metrics.note_decode_harvest(blocking=blocking)
            if cache.active_slots() == 0 and not self._decode_inflight:
                # a chunk entry can be the LAST harvest of a busy
                # period (everything else shed): same idle reset as
                # the decode branch below
                self._reset_stall_gauge()
            return True
        toks_dev = payload
        try:
            toks = np.asarray(toks_dev)      # the sync point
            alive_in = np.asarray(alive_dev)
        except Exception as exc:
            self._decode_fail(exc, snap)
            return False
        self._metrics.note_decode_harvest(blocking=blocking)
        t_sync = time.time()
        self._decode_walls.append(max(t_sync - t_disp, 0.0))
        # inter-token stall gauge (ISSUE 14): the wall gap between
        # consecutive token-block harvests while PREFILL work (a
        # monolithic prefill lot or a chunk dispatch) was in flight,
        # in units of the lane's own min scan wall — "how many step
        # boundaries did an in-flight decode miss to someone's
        # prompt".  Counted only when some REQUEST was decoding across
        # the whole gap (alive at both harvest endpoints — keyed by
        # request identity, not slot index: a slot released and
        # re-admitted between harvests carries a DIFFERENT request
        # whose own prefill is not a stall, it is the prefill).
        # Chunking bounds the gauge at ~one chunk; the monolithic
        # lane pays the whole prompt.
        # the set holds the request OBJECTS (identity hash), not their
        # id()s: a freed request's recycled id could otherwise alias a
        # new admission across the gap
        alive_reqs = frozenset(
            reqs[int(s)]
            for s in np.nonzero(alive_in.any(axis=0))[0]
            if reqs[int(s)] is not None)
        if self._last_harvest_t is not None and \
                self._prefill_since_harvest and \
                (alive_reqs & self._last_harvest_alive):
            gap = max(t_sync - self._last_harvest_t, 0.0)
            floor = min(self._decode_walls) if self._decode_walls \
                else 0.0
            self._metrics.note_decode_stall(
                gap / max(floor, 1e-9), gap)
        self._last_harvest_t = t_sync
        self._last_harvest_alive = alive_reqs
        self._prefill_since_harvest = False
        end_id = self.generation.end_id
        finished = 0
        for s, req in enumerate(reqs):
            if req is None or req.done():
                # freed before this scan dispatched, or already
                # delivered/shed — a dead slot's alive_in column is
                # all-False, so there are no tokens to lose here
                continue
            req.tokens.extend(int(t) for t in toks[alive_in[:, s], s])
            # the scan's own stop rule, replayed host-side: a slot
            # dies when it emits end_id or exhausts its budget — so
            # finish-detection needs no extra device read (the carry's
            # alive leaf stays un-synced, free to chain)
            budget = min(req.max_len, self.generation.max_len)
            done = req.tokens and (req.tokens[-1] == end_id or
                                   len(req.tokens) >= budget)
            if done and req.slot == s:
                if req.trace is not None:
                    req.trace.mark('decode_end', t_sync)
                cache.release(s)
                self._finish_generate(req)
                finished += 1
        self._metrics.note_decode_dispatch(
            k, int(alive_in.sum()), k * cache.slots, finished)
        if cache.active_slots() == 0 and not self._decode_inflight:
            # lane going idle: the NEXT busy period's first harvest
            # must not measure the idle gap as a prefill stall
            self._reset_stall_gauge()
        if _profiler.is_profiler_enabled() or _trace.spans_enabled():
            _profiler.record_event(self._spans + 'decode[x%d]' % k,
                                   time.time() - t_sync, start=t_sync)
        return True

    def _reset_stall_gauge(self):
        """Clear the inter-token stall gauge's episode state (ISSUE
        14) when the decode lane goes idle — by harvest (either kind),
        shed, or a poisoned-chain reset.  Without this, the next busy
        period's first harvest would measure the whole idle gap
        against a STALE _last_harvest_t (and a recycled slot index
        could satisfy the alive-across-both-endpoints guard),
        permanently corrupting the max the chunked_prefill gate and
        the bench/load_gen reports are built on."""
        self._last_harvest_t = None
        self._last_harvest_alive = frozenset()
        self._prefill_since_harvest = False

    def _decode_fail(self, exc, snap):
        """A decode dispatch or harvest failed: the chain behind it is
        poisoned (every later scan consumed the bad carry), so error
        EVERY slotted request, drop the chain, and reset the cache to
        a fresh host-side carry — the worker survives and the next
        admission decodes from clean slabs."""
        self._metrics.note_error()
        _trace.flight_recorder.dump(
            'decode_error:%s' % self.name, error=repr(exc),
            slot_map=snap, chain_depth=len(self._decode_inflight))
        cache = self._decode_cache
        self._decode_inflight.clear()
        for req in cache.active_requests():
            cache.release(req.slot)
            if not req.done():
                req.set_error(exc)
        cache.reset()
        self._reset_stall_gauge()

    def _decode_flush(self):
        """Chain-flush point (ISSUE 9): harvest EVERY in-flight scan so
        the slot map and the carry are consistent — admission, shed
        deactivation and cache eviction mutate slots, and must never
        race a scan that was dispatched against the pre-mutation
        carry.  Returns True unless the chain was poisoned."""
        flushed = bool(self._decode_inflight)
        while self._decode_inflight:
            if not self._decode_harvest_one():
                return False
        if flushed:
            self._metrics.note_decode_flush()
        return True

    def _decode_mirror_alive(self, req):
        """The host's view of whether ``req``'s slot can still be
        alive, from HARVESTED tokens only (in-flight scans unknown —
        conservatively alive): the same stop rule the scan masks."""
        budget = min(req.max_len, self.generation.max_len)
        return len(req.tokens) < budget and (
            not req.tokens or req.tokens[-1] != self.generation.end_id)

    def _decode_should_dispatch(self):
        """Dispatch another scan only when some occupied slot can
        still be alive AFTER the scans already in flight: a request's
        remaining budget is deterministic (EOS only ends it sooner),
        so when every active request's budget is provably consumed by
        in-flight steps, another scan could only run frozen slots —
        harvest instead."""
        active = self._decode_cache.active_requests()
        if not active:
            return False
        for req in active:
            if req.prefilling:
                # a PREFILLING slot (ISSUE 14) is inert in the scan
                # (alive=False) until its finishing chunk dispatches —
                # it must not justify a scan of frozen slots
                continue
            if not self._decode_mirror_alive(req):
                continue
            budget = min(req.max_len, self.generation.max_len)
            inflight_steps = sum(
                e[3] for e in self._decode_inflight
                if e[0] == 'decode' and req in e[5])
            if budget - len(req.tokens) - inflight_steps > 0:
                return True
        return False

    def _decode_doomed(self):
        """Active generations whose deadline lands before even the
        NEXT step boundary — one measured scan wall away — can arrive
        (ISSUE 8, sharpened by ISSUE 9): any further tokens would be
        late anyway, so the slot is better spent on a live request.
        ONE predicate shared by _decode_needs_flush and the shed loop:
        if the two drifted, needs-flush could trip every cycle while
        the shed loop sheds nothing — silently degrading the chain to
        per-scan sync with token-identical outputs (no test would
        trip).  EDF only; 'fifo' never sheds."""
        if self.config.scheduling != 'edf':
            return []
        now = time.time()
        est = min(self._decode_walls) if self._decode_walls else 0.0
        return [req for req in self._decode_cache.active_requests()
                if req.deadline_t is not None and
                now + est > req.deadline_t]

    def _decode_needs_flush(self):
        """True when the next cycle must mutate slots: a deadlined
        active generation to shed, or prefilled requests with a free
        slot to admit into.  Deliberately NOT 'prefills waiting but no
        slot free': forcing a flush every cycle to poll for releases
        would degrade the chain to the per-scan-sync lane exactly when
        a backlog queues — the opportunistic and backpressure harvests
        already release finished slots as the chain advances, and the
        free slot trips this check on the next cycle."""
        cache = self._decode_cache
        if (self._gen_ready or self._chunk_pending) and \
                cache.free_slots():
            return True
        return bool(self._decode_doomed())

    def _decode_cycle(self):
        """One decode-lane turn (ISSUE 9, pipelined): flush the chain
        when admission or shedding must mutate slots, enqueue the next
        chained scan FIRST, then harvest the oldest in-flight scan
        behind it — the dispatch-before-harvest order is the whole
        point: scan N+1 is already queued on device while the host
        syncs N's token block, so the harvest round trip never idles
        the device.  decode_pipeline_depth=1 degenerates to the PR 7
        per-scan-sync lane: dispatch, harvest, repeat.  Returns True
        when the lane made progress (dispatched, harvested, admitted
        or shed)."""
        cache = self._decode_cache
        if cache is None:
            return False
        progressed = False
        if self._decode_needs_flush():
            progressed = True
            if not self._decode_flush():
                return True
            # shed at the flushed boundary: the chain is empty, so
            # deactivation mutates a consistent carry (the doomed
            # predicate is shared with _decode_needs_flush)
            for req in self._decode_doomed():
                slot = req.slot
                cache.release(slot)
                cache.deactivate(slot)
                if req.trace is not None:
                    req.trace.add_count('decode_steps',
                                        len(req.tokens))
                self._shed_request(req, where='decode')
            if cache.active_slots() == 0:
                # sheds can empty the lane with no harvest to follow:
                # the chain is flushed here, so idle-reset the stall
                # gauge before fresh admissions start a new episode
                self._reset_stall_gauge()
            self._admit_ready()
            if self._chunking:
                self._admit_chunk_pending()
        dispatched = False
        if self._decode_should_dispatch():
            dispatched = self._decode_dispatch()
            progressed = dispatched or progressed
        # at most ONE prefill chunk rides each cycle, AFTER the decode
        # dispatch (decode priority — ISSUE 14); it chains on the same
        # carry, so the max decode stall it can add is one chunk wall
        if self._chunk_should_dispatch():
            chunked = self._chunk_dispatch()
            dispatched = dispatched or chunked
            progressed = chunked or progressed
        if not dispatched:
            # nothing worth another dispatch: drain the chain so
            # finished requests deliver and their slots free
            while self._decode_inflight:
                progressed = True
                if not self._decode_harvest_one():
                    return True
        # pipeline backpressure: at most decode_pipeline_depth
        # dispatches in flight — the oldest harvests while the newest
        # computes
        while len(self._decode_inflight) >= \
                self.config.decode_pipeline_depth:
            progressed = True
            if not self._decode_harvest_one():
                break
        return progressed

    def _finish_generate(self, req):
        """Deliver one finished generation request: token ids out,
        trace finalized (prefill/decode/detokenize stages + the
        decode_steps count) BEFORE the future resolves."""
        out = np.asarray(req.tokens, np.int64)
        if req.trace is not None:
            req.trace.add_count('decode_steps', len(req.tokens))
            self._metrics.note_stages(req.trace.finalize())
            _trace.record_span(
                self._spans + 'generate', req.trace.t0,
                req.trace.e2e_s, trace_id=req.trace_id)
        req.set_result(out)
        self._drains.note()
        if req.latency_s is not None:
            self._metrics.note_latency(req.latency_s)

    def _gen_busy(self):
        """True while the generation lane has work: prefilled (or
        chunk-pending) requests awaiting slots, slots actively decoding
        or prefilling, or in-flight chained dispatches awaiting
        harvest."""
        return self._decode_cache is not None and (
            bool(self._gen_ready) or bool(self._chunk_pending) or
            bool(self._decode_inflight) or
            self._decode_cache.any_active())

    def evict_decode_cache(self):
        """Demote the decode slot cache to host memory under a
        paused() window (bitwise — in-flight generations resume exactly
        after transparent re-staging) and drop the prefill/step
        executables.  Returns bytes moved — the registry's arbiter
        calls this to release an idle generation model's slabs."""
        if self._decode_cache is None:
            return 0
        with self.paused():
            moved = self._decode_cache.to_host()
            programs = [self.generation.prefill_program,
                        self.generation.step_program]
            if self.generation.chunk_program is not None:
                programs.append(self.generation.chunk_program)
            self.drop_executables(programs=programs)
        return moved

    # ---- worker -------------------------------------------------------

    def _safe_make_lot(self, requests):
        """_make_lot that fails the LOT, not the worker: a malformed
        request must error its own future and leave the engine serving
        (an unhandled exception here would kill the daemon thread and
        strand every later caller)."""
        try:
            return self._make_lot(requests)
        except Exception as exc:
            self._metrics.note_error()
            for req in requests:
                req.set_error(exc)
            return None

    def _route_chunked(self, reqs):
        """Chunked-prefill routing (ISSUE 14): under
        ``prefill_chunk=C`` a generation lot never forms — the prompt
        tokens were captured at submit, so the requests queue for a
        PREFILLING slot and their prompts ride chunk dispatches
        instead of a prefill-program lot.  (They still travel the
        batcher for wake-ups, EDF ordering and queue-shed semantics.)
        Returns the requests that still need a lot; None when all were
        routed to the chunk lane."""
        if not self._chunking or not reqs or reqs[0].kind != 'generate':
            return reqs
        now = time.time()
        for req in reqs:
            if req.trace is not None:
                req.trace.mark('collect', now)
            self._chunk_pending.append(req)
        return None

    def _collect_block(self, first_lot):
        """Extend a block with already-flushable same-bucket lots, then
        TRIM to a power-of-two lot count (extras go back on the carry
        queue): `steps` is a static jit argument of the eval scan, so a
        free-running 1..K count would mint up to K executables per
        bucket under fluctuating traffic — the quantized ladder bounds
        it at log2(K)+1."""
        lots = [first_lot]
        while len(lots) < self.config.steps_per_dispatch:
            if self._carry:
                lot = self._carry.popleft()
            else:
                more = self._batcher.next_lot(timeout=0)
                if not more:
                    break
                lot = self._safe_make_lot(more)
                if lot is None:
                    continue
            if lot.sig != lots[0].sig:
                self._carry.appendleft(lot)
                break
            lots.append(lot)
        k = 1
        while k * 2 <= len(lots):
            k *= 2
        self._carry.extend(lots[k:])
        return lots[:k]

    def _serve_loop(self):
        poll = max(min(self.config.max_wait_s, 0.005), 0.001)
        while True:
            try:
                reqs = []
                if not self._carry:
                    # idle engine blocks on the queue's condition var
                    # (submit/close notify) OUTSIDE the cycle lock, so a
                    # paused() window never has to wait for traffic; an
                    # awaiting in-flight dispatch — or a busy decode
                    # lane, which must keep stepping between arrivals —
                    # warrants the short drain poll
                    reqs = self._batcher.next_lot(
                        timeout=poll if (self._inflight or
                                         self._gen_busy()) else None)
                    if reqs is None:
                        break  # closed and drained
                # one collect->dispatch->drain->decode cycle is the
                # pause unit: paused() holds the cycle lock while
                # weights move, and the worker parks HERE between cycles
                with self._cycle_lock:
                    if reqs:
                        reqs = self._route_chunked(reqs)
                    if self._carry and not reqs:
                        self._dispatch(
                            self._collect_block(self._carry.popleft()))
                    elif reqs:
                        lot = self._safe_make_lot(reqs)
                        if lot is not None:
                            self._dispatch(self._collect_block(lot))
                    elif self._inflight and not self._gen_busy():
                        self._drain_one()  # idle: deliver early
                    # pipeline backpressure: keep at most pipeline_depth
                    # dispatches in flight — host feeds N+1 while N
                    # computes
                    while len(self._inflight) >= self.config.pipeline_depth:
                        self._drain_one()
                    if self._decode_cache is not None:
                        # deliver completed dispatches even while the
                        # decode lane is busy: a forward future ready
                        # after one cycle must not wait out every
                        # active generation, and a prefill stuck in
                        # the pipeline while slots sit free starves
                        # admission
                        if self._inflight and self._gen_busy():
                            self._drain_one()
                        # one decode scan per cycle: forward lots and
                        # decode steps interleave on the worker, so
                        # neither lane can starve the other
                        self._decode_cycle()
            except Exception as exc:
                # belt-and-braces: _dispatch/_drain_one already error
                # their own lots' futures; whatever still escapes must
                # not kill the serving thread
                self._metrics.note_error()
                _trace.flight_recorder.dump(
                    'worker_error:%s' % self.name, error=repr(exc))
        with self._cycle_lock:
            while self._carry:
                self._dispatch([self._carry.popleft()])
            while self._inflight:
                self._drain_one()
            # run the generation lane dry: admitted requests decode to
            # their stop conditions, prefilled ones admit as slots
            # free, and the in-flight chain harvests to empty
            while self._gen_busy():
                if not self._decode_cycle():
                    break
            if self._decode_cache is not None:
                self._decode_flush()

    def _drain_inline(self):
        """Synchronous mode: flush + dispatch + deliver on the calling
        thread (no micro-batching across callers, no pipelining).
        Serialized by _inline_lock — concurrent submitters to a
        never-start()ed engine must not interleave on _inflight/_carry."""
        with self._inline_lock:
            while True:
                progressed = False
                if self._carry:
                    self._dispatch(
                        self._collect_block(self._carry.popleft()))
                    progressed = True
                else:
                    reqs = self._batcher.next_lot(timeout=0, force=True)
                    if reqs:
                        reqs = self._route_chunked(reqs)
                        if reqs:
                            lot = self._safe_make_lot(reqs)
                            if lot is not None:
                                self._dispatch(self._collect_block(lot))
                        progressed = True
                while self._inflight:
                    self._drain_one()
                    progressed = True
                # generation work drains synchronously too: decode
                # cycles run until every submitted request finished
                # (inline mode has no worker to step the lane later)
                if self._gen_busy():
                    progressed = self._decode_cycle() or progressed
                if not progressed and not self._carry:
                    break
