"""Legacy composite networks (reference
trainer_config_helpers/networks.py): simple_lstm / simple_gru /
simple_img_conv_pool as layer compositions."""

from . import layers as _l

__all__ = ['simple_lstm', 'simple_gru', 'simple_img_conv_pool']


def simple_lstm(input, size, name=None, **kwargs):
    """fc gate projection + lstmemory (reference networks.py:632
    simple_lstm)."""
    proj = _l.fc_layer(input=input, size=size * 4)
    return _l.lstmemory(input=proj, size=size, name=name)


def simple_gru(input, size, name=None, **kwargs):
    return _l.grumemory(input=input, size=size, name=name)


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride=1, act=None, name=None, **kwargs):
    conv = _l.img_conv_layer(input=input, filter_size=filter_size,
                             num_filters=num_filters, act=act)
    return _l.img_pool_layer(input=conv, pool_size=pool_size,
                             stride=pool_stride, name=name)
