"""Legacy composite networks (reference trainer_config_helpers/
networks.py — 1587 LoC of layer compositions; this file carries the
presets the book/demo configs used: conv stacks through VGG-16,
uni/bidirectional recurrent nets, and the attention blocks)."""

from . import layers as _l
from .activations import LinearActivation
from .poolings import MaxPooling
from ..v2 import layer as _v2

__all__ = [
    'simple_lstm', 'simple_gru', 'simple_gru2', 'simple_img_conv_pool',
    'img_conv_bn_pool', 'img_conv_group', 'vgg_16_network',
    'bidirectional_lstm', 'bidirectional_gru', 'simple_attention',
    'dot_product_attention', 'sequence_conv_pool', 'text_conv_pool',
]


def simple_lstm(input, size, name=None, reverse=False,
                mat_param_attr=None, bias_param_attr=None,
                inner_param_attr=None, **kwargs):
    """fc gate projection + lstmemory (reference networks.py:632
    simple_lstm: the size*4 transform is a bias-free LINEAR
    mixed_layer — fc_layer's Tanh default must not squash the gate
    pre-activations).  mat_param_attr is the projection weight,
    inner_param_attr/bias_param_attr the recurrence's."""
    proj = _l.fc_layer(input=input, size=size * 4,
                       act=LinearActivation(), bias_attr=False,
                       param_attr=mat_param_attr)
    return _l.lstmemory(input=proj, size=size, name=name, reverse=reverse,
                        param_attr=inner_param_attr,
                        bias_attr=bias_param_attr)


def _gru_block(input, size, name, reverse, mixed_param_attr,
               mixed_bias_attr, gru_param_attr, gru_bias_attr):
    """Shared body of simple_gru/simple_gru2 (identical structure in
    the reference, differing only in kwarg spelling): one explicit
    LINEAR size*3 projection feeding the raw GRU."""
    proj = _l.fc_layer(input=input, size=size * 3,
                       act=LinearActivation(),
                       param_attr=mixed_param_attr,
                       bias_attr=mixed_bias_attr)
    return _l.grumemory(input=proj, size=size, name=name,
                        reverse=reverse, param_attr=gru_param_attr,
                        bias_attr=gru_bias_attr, project=False)


def simple_gru(input, size, name=None, reverse=False,
               mixed_param_attr=None, mixed_bias_param_attr=None,
               gru_param_attr=None, gru_bias_attr=None, **kwargs):
    """reference gru_group/simple_gru (networks.py:1076 — note the
    reference spells the projection bias kwarg mixed_bias_PARAM_attr
    here but mixed_bias_attr on simple_gru2)."""
    return _gru_block(input, size, name, reverse, mixed_param_attr,
                      mixed_bias_param_attr, gru_param_attr,
                      gru_bias_attr)


def simple_gru2(input, size, name=None, reverse=False,
                mixed_param_attr=None, mixed_bias_attr=None,
                gru_param_attr=None, gru_bias_attr=None, **kwargs):
    """reference simple_gru2 (networks.py:1163): same structure as
    simple_gru, reference-spelled kwargs."""
    return _gru_block(input, size, name, reverse, mixed_param_attr,
                      mixed_bias_attr, gru_param_attr, gru_bias_attr)


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride=1, act=None, name=None, **kwargs):
    conv = _l.img_conv_layer(input=input, filter_size=filter_size,
                             num_filters=num_filters, act=act)
    return _l.img_pool_layer(input=conv, pool_size=pool_size,
                             stride=pool_stride, name=name)


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     pool_stride=1, act=None, name=None,
                     num_channel=None, conv_padding=0,
                     conv_param_attr=None, conv_bias_attr=None,
                     bn_param_attr=None, bn_bias_attr=None, **kwargs):
    """conv + batch_norm + pool (reference img_conv_bn_pool: the conv
    is explicitly LINEAR — reference networks.py:308 — so the only
    nonlinearity is the one batch_norm applies)."""
    conv = _l.img_conv_layer(input=input, filter_size=filter_size,
                             num_filters=num_filters,
                             num_channels=num_channel,
                             padding=conv_padding,
                             act=LinearActivation(),
                             param_attr=conv_param_attr,
                             bias_attr=conv_bias_attr)
    bn = _l.batch_norm_layer(input=conv, act=act,
                             param_attr=bn_param_attr,
                             bias_attr=bn_bias_attr)
    return _l.img_pool_layer(input=bn, pool_size=pool_size,
                             stride=pool_stride, name=name)


def img_conv_group(input, conv_num_filter, pool_size, conv_filter_size=3,
                   conv_act=None, conv_with_batchnorm=False,
                   pool_stride=2, num_channels=None, name=None, **kwargs):
    """N stacked convs closed by one pool (reference img_conv_group)."""
    tmp = input
    if not isinstance(conv_num_filter, (list, tuple)):
        conv_num_filter = [conv_num_filter]
    for i, nf in enumerate(conv_num_filter):
        tmp = _l.img_conv_layer(
            input=tmp, filter_size=conv_filter_size, num_filters=nf,
            num_channels=num_channels if i == 0 else None,
            padding=(conv_filter_size - 1) // 2,
            # under batch_norm the conv is explicitly LINEAR (reference
            # networks.py:410) and conv_act moves onto the BN
            act=LinearActivation() if conv_with_batchnorm else conv_act)
        if conv_with_batchnorm:
            tmp = _l.batch_norm_layer(input=tmp, act=conv_act)
    return _l.img_pool_layer(input=tmp, pool_size=pool_size,
                             stride=pool_stride, name=name)


def vgg_16_network(input_image, num_channels, num_classes=1000,
                   **kwargs):
    """VGG-16 (reference networks.py vgg_16_network): five conv groups
    (2-2-3-3-3 convs of 64/128/256/512/512 filters, each closed by a
    2x2 pool), two fc-4096 + dropout, softmax head."""
    from .activations import ReluActivation, SoftmaxActivation
    tmp = input_image
    for gi, (filters, depth) in enumerate(((64, 2), (128, 2), (256, 3),
                                           (512, 3), (512, 3))):
        tmp = img_conv_group(
            input=tmp, conv_num_filter=[filters] * depth, pool_size=2,
            conv_filter_size=3, conv_act=ReluActivation(),
            conv_with_batchnorm=True, pool_stride=2,
            num_channels=num_channels if gi == 0 else None)
    for _ in range(2):
        tmp = _l.fc_layer(input=tmp, size=4096, act=ReluActivation())
        tmp = _l.dropout_layer(input=tmp, dropout_rate=0.5)
    return _l.fc_layer(input=tmp, size=num_classes,
                       act=SoftmaxActivation())


def bidirectional_lstm(input, size, return_seq=False, name=None,
                       fwd_mat_param_attr=None, fwd_bias_param_attr=None,
                       fwd_inner_param_attr=None,
                       bwd_mat_param_attr=None, bwd_bias_param_attr=None,
                       bwd_inner_param_attr=None, **kwargs):
    """Forward + backward lstmemory, concatenated — delegates each arm
    to simple_lstm exactly as the reference does (networks.py:1368),
    so the bias-free LINEAR gate projection is defined in one place."""
    fwd = simple_lstm(input=input, size=size,
                      mat_param_attr=fwd_mat_param_attr,
                      bias_param_attr=fwd_bias_param_attr,
                      inner_param_attr=fwd_inner_param_attr)
    bwd = simple_lstm(input=input, size=size, reverse=True,
                      mat_param_attr=bwd_mat_param_attr,
                      bias_param_attr=bwd_bias_param_attr,
                      inner_param_attr=bwd_inner_param_attr)
    if return_seq:
        return _l.concat_layer(input=[fwd, bwd], name=name)
    return _l.concat_layer(
        input=[_l.last_seq(input=fwd), _l.first_seq(input=bwd)],
        name=name)


def bidirectional_gru(input, size, return_seq=False, name=None,
                      fwd_mixed_param_attr=None, fwd_mixed_bias_attr=None,
                      fwd_gru_param_attr=None, fwd_gru_bias_attr=None,
                      bwd_mixed_param_attr=None, bwd_mixed_bias_attr=None,
                      bwd_gru_param_attr=None, bwd_gru_bias_attr=None,
                      **kwargs):
    """Forward + backward GRU arms, each the reference's projected
    gru block (networks.py:1226 forwards per-arm mixed/gru attrs)."""
    fwd = _gru_block(input, size, None, False, fwd_mixed_param_attr,
                     fwd_mixed_bias_attr, fwd_gru_param_attr,
                     fwd_gru_bias_attr)
    bwd = _gru_block(input, size, None, True, bwd_mixed_param_attr,
                     bwd_mixed_bias_attr, bwd_gru_param_attr,
                     bwd_gru_bias_attr)
    if return_seq:
        return _l.concat_layer(input=[fwd, bwd], name=name)
    return _l.concat_layer(
        input=[_l.last_seq(input=fwd), _l.first_seq(input=bwd)],
        name=name)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     name=None, **kwargs):
    """Bahdanau-style additive attention (reference networks.py
    simple_attention): score = fc(tanh(proj + expand(decoder_state))),
    context = sum(softmax(score) * encoded_sequence)."""
    from .. import fluid

    def build(ctx, seq_var, proj_var, state_var):
        dec = fluid.layers.fc(state_var, size=proj_var.shape[-1],
                              bias_attr=False)
        dec_seq = fluid.layers.sequence_expand(dec, proj_var)
        mix = fluid.layers.tanh(
            fluid.layers.elementwise_add(proj_var, dec_seq))
        # score vector v: e[b,t] = <v, mix[b,t,:]> (the fc-to-1 of the
        # reference, written shape-agnostically over the padded layout)
        d = int(proj_var.shape[-1])
        vparam = fluid.layers.create_parameter(shape=[d], dtype='float32')
        e = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(mix, vparam, axis=-1),
            dim=-1, keep_dim=True)
        w = fluid.layers.sequence_softmax(e)
        scaled = fluid.layers.elementwise_mul(seq_var, w, axis=0)
        return fluid.layers.sequence_pool(scaled, pool_type='sum')

    return _v2.Layer(
        'simple_attention',
        [encoded_sequence, encoded_proj, decoder_state], build,
        name=name, size=encoded_sequence.size)


def dot_product_attention(attended_sequence, attending_sequence,
                          transformed_state, name=None, **kwargs):
    """Dot-product attention (reference networks.py
    dot_product_attention)."""
    from .. import fluid

    def build(ctx, attended_var, attending_var, state_var):
        expanded = fluid.layers.sequence_expand(state_var, attending_var)
        e = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(attending_var, expanded),
            dim=-1, keep_dim=True)
        w = fluid.layers.sequence_softmax(e)
        scaled = fluid.layers.elementwise_mul(attended_var, w, axis=0)
        return fluid.layers.sequence_pool(scaled, pool_type='sum')

    return _v2.Layer(
        'dot_product_attention',
        [attended_sequence, attending_sequence, transformed_state],
        build, name=name, size=attended_sequence.size)


def sequence_conv_pool(input, context_len, hidden_size,
                       pool_type=None, name=None, **kwargs):
    """Context projection + fc + sequence pool (reference
    sequence_conv_pool — the text-CNN block)."""
    proj = _l.mixed_layer(
        size=input.size * context_len,
        input=[_l.context_projection(input, context_len=context_len)])
    hidden = _l.fc_layer(input=proj, size=hidden_size)
    return _l.pooling_layer(input=hidden,
                            pooling_type=pool_type or MaxPooling(),
                            name=name)


text_conv_pool = sequence_conv_pool
