"""Legacy settings()/optimizer DSL (reference
trainer_config_helpers/optimizers.py:358 settings).

settings() records the module-level training config the old trainer
binary would have parsed; ``get_settings()``/``make_v2_optimizer()``
expose it to the executable v2 flow."""

from ..v2 import optimizer as _v2_opt

__all__ = [
    'settings', 'get_settings', 'make_v2_optimizer', 'AdamOptimizer',
    'AdamaxOptimizer', 'MomentumOptimizer', 'RMSPropOptimizer',
    'AdaGradOptimizer', 'BaseSGDOptimizer', 'DecayedAdaGradOptimizer',
    'AdaDeltaOptimizer', 'BaseRegularization', 'L2Regularization',
    'ModelAverage', 'GradientClippingThreshold',
]

_SETTINGS = {}


class BaseSGDOptimizer(object):
    kwargs = {}

    def to_v2(self, learning_rate):
        raise NotImplementedError


class MomentumOptimizer(BaseSGDOptimizer):
    def __init__(self, momentum=0.9, **kwargs):
        self.momentum = momentum

    def to_v2(self, learning_rate):
        return _v2_opt.Momentum(momentum=self.momentum,
                                learning_rate=learning_rate)


class AdamOptimizer(BaseSGDOptimizer):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def to_v2(self, learning_rate):
        return _v2_opt.Adam(beta1=self.beta1, beta2=self.beta2,
                            epsilon=self.epsilon,
                            learning_rate=learning_rate)


class AdamaxOptimizer(BaseSGDOptimizer):
    def __init__(self, beta1=0.9, beta2=0.999, **kwargs):
        self.beta1, self.beta2 = beta1, beta2

    def to_v2(self, learning_rate):
        return _v2_opt.Adamax(beta1=self.beta1, beta2=self.beta2,
                              learning_rate=learning_rate)


class RMSPropOptimizer(BaseSGDOptimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        self.rho, self.epsilon = rho, epsilon

    def to_v2(self, learning_rate):
        return _v2_opt.RMSProp(rho=self.rho, epsilon=self.epsilon,
                               learning_rate=learning_rate)


class AdaGradOptimizer(BaseSGDOptimizer):
    def to_v2(self, learning_rate):
        return _v2_opt.AdaGrad(learning_rate=learning_rate)


class DecayedAdaGradOptimizer(BaseSGDOptimizer):
    """(reference optimizers.py:235)"""

    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        self.rho, self.epsilon = rho, epsilon

    def to_v2(self, learning_rate):
        return _v2_opt.DecayedAdaGrad(rho=self.rho, epsilon=self.epsilon,
                                      learning_rate=learning_rate)


class AdaDeltaOptimizer(BaseSGDOptimizer):
    """(reference optimizers.py:263)"""

    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        self.rho, self.epsilon = rho, epsilon

    def to_v2(self, learning_rate):
        return _v2_opt.AdaDelta(rho=self.rho, epsilon=self.epsilon,
                                learning_rate=learning_rate)


class BaseRegularization(object):
    """(reference optimizers.py:294)"""

    def __init__(self, rate=0.0):
        self.rate = rate


class L2Regularization(BaseRegularization):
    """settings(regularization=L2Regularization(1e-4)) — forwarded into
    the v2 optimizer's regularization slot."""


class ModelAverage(object):
    """(reference optimizers.py:319) — average_window config carried to
    the v2 optimizer surface."""

    def __init__(self, average_window, max_average_window=None, **kwargs):
        self.average_window = average_window
        self.max_average_window = max_average_window


class GradientClippingThreshold(object):
    """(reference optimizers.py:336) — records the global clipping
    threshold; settings() already accepts
    gradient_clipping_threshold=<float> directly, this object form is
    the reference's extra_settings spelling."""

    def __init__(self, threshold, **kwargs):
        self.threshold = threshold

    def __float__(self):
        return float(self.threshold)


def settings(batch_size,
             learning_rate=1e-3,
             learning_method=None,
             regularization=None,
             gradient_clipping_threshold=None,
             **kwargs):
    """(reference optimizers.py:358) Record the training configuration."""
    _SETTINGS.clear()
    _SETTINGS.update(
        batch_size=batch_size,
        learning_rate=learning_rate,
        learning_method=learning_method,
        regularization=regularization,
        gradient_clipping_threshold=gradient_clipping_threshold)
    _SETTINGS.update(kwargs)


def get_settings():
    return dict(_SETTINGS)


def make_v2_optimizer():
    """The recorded settings as a v2 optimizer (SGD when no
    learning_method was set).  A recorded ``regularization`` rides into
    the v2 optimizer's regularization slot (L2Decay at the fluid
    level)."""
    lr = _SETTINGS.get('learning_rate', 1e-3)
    method = _SETTINGS.get('learning_method')
    opt = (_v2_opt.Momentum(momentum=0.0, learning_rate=lr)
           if method is None else method.to_v2(lr))
    reg = _SETTINGS.get('regularization')
    if reg is not None:
        rate = getattr(reg, 'rate', None)
        if rate is None:
            raise TypeError(
                'settings(regularization=...) expects an L2Regularization '
                '(tch or v2 flavor, both carry .rate); got %r' % (reg, ))
        if rate:
            opt.kwargs['regularization'] = _v2_opt.L2Regularization(rate)
    return opt
