"""define_py_data_sources2 (reference:
trainer_config_helpers/data_sources.py) — the config-file hook binding a
PyDataProvider2 module/function to train/test file lists.

The reference records a PyData proto block the trainer binary resolved
at startup; here the binding resolves immediately to DataProvider-backed
readers, exposed via ``get_data_sources()`` for the executable v2 flow
(and recorded into settings for get_config() consumers)."""

import importlib

__all__ = ['define_py_data_sources2', 'get_data_sources']

_DATA_SOURCES = {}


def _load_file_list(file_list):
    if isinstance(file_list, (list, tuple)):
        return list(file_list)
    with open(file_list) as f:
        return [l.strip() for l in f if l.strip()]


def _resolve(module, obj, args):
    if isinstance(module, str):
        module = importlib.import_module(module)
    dp = getattr(module, obj) if isinstance(obj, str) else obj
    # reference passes args through the init_hook kwargs; re-bind any
    # the config supplies on top of what provider() bound
    if args:
        for k, v in args.items():
            setattr(dp.settings, k, v)
    return dp


def define_py_data_sources2(train_list, test_list, module, obj,
                            args=None):
    """(reference data_sources.py define_py_data_sources2) Bind the
    provider ``obj`` in ``module`` to the train/test file lists.

    ``module`` may be a module object or import path; ``obj`` the
    provider name (or the DataProvider itself).  ``train_list`` /
    ``test_list`` are list files (one data path per line) or direct
    lists of paths; either may be None."""
    _DATA_SOURCES.clear()
    for split, flist in (('train', train_list), ('test', test_list)):
        if flist is None:
            continue
        dp = _resolve(module, obj, args)
        _DATA_SOURCES[split] = dp.as_reader(_load_file_list(flist))


def get_data_sources():
    """{'train': reader, 'test': reader} bound by the last
    define_py_data_sources2 call (the single source of truth —
    get_config()'s settings dict does not duplicate it)."""
    return dict(_DATA_SOURCES)


def reset_data_sources():
    _DATA_SOURCES.clear()
