"""Legacy evaluator DSL (reference
trainer_config_helpers/evaluators.py:18 — evaluators attach metric
computations to a config's output layers).

Each evaluator returns a v2 DAG node computing the metric through the
fluid metric ops (layers/metric_op.py), so legacy configs that attach
evaluators get real, fetchable metric values from the same compiled
program."""

from ..v2 import layer as _v2
from .. import fluid

__all__ = [
    'classification_error_evaluator', 'auc_evaluator',
    'ctc_error_evaluator', 'chunk_evaluator', 'sum_evaluator',
    'column_sum_evaluator', 'precision_recall_evaluator',
    'pnpair_evaluator', 'detection_map_evaluator',
    'value_printer_evaluator', 'gradient_printer_evaluator',
    'maxid_printer_evaluator', 'maxframe_printer_evaluator',
    'seqtext_printer_evaluator', 'classification_error_printer_evaluator',
]


def _metric_layer(kind, parents, build, name):
    layer = _v2.Layer(kind, parents, build, name=name)
    layer.is_evaluator = True
    return layer


def classification_error_evaluator(input, label, name=None, **kwargs):
    """Error rate = 1 - accuracy (reference evaluators.py:220)."""

    def build(ctx, input_var, label_var):
        acc = fluid.layers.accuracy(input=input_var, label=label_var)
        return fluid.layers.scale(acc, scale=-1.0, bias=1.0)

    return _metric_layer('classification_error', [input, label], build,
                         name)


def auc_evaluator(input, label, name=None, **kwargs):
    """(reference evaluators.py:272)"""

    def build(ctx, input_var, label_var):
        auc_out, _, _ = fluid.layers.auc(input=input_var, label=label_var)
        return auc_out

    return _metric_layer('auc', [input, label], build, name)


def ctc_error_evaluator(input, label, name=None, **kwargs):
    """Edit-distance between CTC decodes and labels
    (reference evaluators.py:398)."""

    def build(ctx, input_var, label_var):
        decoded = fluid.layers.ctc_greedy_decoder(input=input_var,
                                                  blank=0)
        dist, _ = fluid.layers.edit_distance(decoded, label_var)
        return fluid.layers.mean(dist)

    return _metric_layer('ctc_error', [input, label], build, name)


def chunk_evaluator(input, label, chunk_scheme='IOB', num_chunk_types=1,
                    name=None, **kwargs):
    """Chunk F1 (reference evaluators.py:425)."""

    def build(ctx, input_var, label_var):
        _, _, f1, _, _, _ = fluid.layers.chunk_eval(
            input=input_var, label=label_var,
            chunk_scheme=chunk_scheme.lower(),
            num_chunk_types=num_chunk_types)
        return f1

    return _metric_layer('chunk_f1', [input, label], build, name)


def sum_evaluator(input, name=None, **kwargs):
    def build(ctx, input_var):
        return fluid.layers.reduce_sum(input_var)

    return _metric_layer('sum', [input], build, name)


def column_sum_evaluator(input, name=None, **kwargs):
    def build(ctx, input_var):
        return fluid.layers.reduce_sum(input_var, dim=0)

    return _metric_layer('column_sum', [input], build, name)


def precision_recall_evaluator(input, label, positive_label=None,
                               name=None, **kwargs):
    """Precision/recall/F1 (reference evaluators.py:353 ->
    operators/precision_recall_op.cc).  Without ``positive_label``:
    the op's macro-averaged [precision, recall, F1] vector (shape (3,)).
    With ``positive_label``: binary metrics for that class, the
    reference's single-class mode."""

    def build(ctx, input_var, label_var):
        if positive_label is None:
            return fluid.layers.precision_recall(
                input=input_var, label=label_var)
        # binary mode: metrics for the one positive class
        _, idx = fluid.layers.topk(input_var, 1)
        pos = float(positive_label)
        pred_pos = fluid.layers.cast(
            fluid.layers.equal(
                fluid.layers.cast(idx, 'float32'),
                fluid.layers.fill_constant_batch_size_like(
                    label_var, shape=[-1, 1], value=pos,
                    dtype='float32')), 'float32')
        lbl_pos = fluid.layers.cast(
            fluid.layers.equal(
                fluid.layers.cast(label_var, 'float32'),
                fluid.layers.fill_constant_batch_size_like(
                    label_var, shape=[-1, 1], value=pos,
                    dtype='float32')), 'float32')
        tp = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(pred_pos, lbl_pos))
        pred_n = fluid.layers.reduce_sum(pred_pos)
        lbl_n = fluid.layers.reduce_sum(lbl_pos)
        eps = fluid.layers.fill_constant(shape=[1], dtype='float32',
                                         value=1e-12)
        precision = fluid.layers.elementwise_div(
            tp, fluid.layers.elementwise_max(pred_n, eps))
        recall = fluid.layers.elementwise_div(
            tp, fluid.layers.elementwise_max(lbl_n, eps))
        f1 = fluid.layers.elementwise_div(
            fluid.layers.scale(
                fluid.layers.elementwise_mul(precision, recall),
                scale=2.0),
            fluid.layers.elementwise_max(
                fluid.layers.elementwise_add(precision, recall), eps))
        return fluid.layers.concat(
            [fluid.layers.reshape(v, shape=[1])
             for v in (precision, recall, f1)], axis=0)

    return _metric_layer('precision_recall', [input, label], build, name)


def pnpair_evaluator(input, label, query_id, name=None, **kwargs):
    """Positive-negative pair stat per query (reference
    evaluators.py:306 -> operators/positive_negative_pair_op.cc).
    Returns one [3] vector: [positive, negative, neutral] pair counts
    (one fetchable var, like every evaluator)."""

    def build(ctx, input_var, label_var, qid_var):
        pos, neg, neu = fluid.layers.positive_negative_pair(
            score=input_var, label=label_var, query_id=qid_var)
        return fluid.layers.concat(
            [fluid.layers.reshape(v, shape=[1])
             for v in (pos, neg, neu)], axis=0)

    return _metric_layer('pnpair', [input, label, query_id], build, name)


def detection_map_evaluator(input, label, num_classes, background_id=0,
                            overlap_threshold=0.5, name=None, **kwargs):
    """Detection mAP (reference evaluators.py:170 ->
    operators/detection_map_op.cc); ``input`` is the detection output
    [N, 6] rows, ``label`` the ground-truth rows, ``num_classes`` the
    class count the mAP averages over."""

    def build(ctx, det_var, gt_var):
        return fluid.layers.detection_map(
            det_var, gt_var, int(num_classes),
            background_label=background_id,
            overlap_threshold=overlap_threshold)

    return _metric_layer('detection_map', [input, label], build, name)


# ---- printer evaluators (reference evaluators.py:589-787): debugging
# evaluators that print tensors during execution; all ride the 'print'
# host op like layers.Print ----
def _printer(kind, layers_in, name, transform=None):
    def build(ctx, *vs):
        out = vs[0] if transform is None else transform(*vs)
        return fluid.layers.Print(out, message='[%s]' % kind)

    return _metric_layer(kind, list(layers_in), build, name)


def value_printer_evaluator(input, name=None, **kwargs):
    return _printer('value_printer', [input], name)


def gradient_printer_evaluator(input, name=None, **kwargs):
    """Documented delta: the reference prints the layer's GRADIENT; here
    gradients are fused inside the compiled backward and are not
    addressable per-layer, so this prints the layer's forward value
    under the gradient_printer tag (attach it for placement parity,
    use FLAGS_check_nan_inf for gradient diagnostics)."""
    return _printer('gradient_printer', [input], name)


def maxid_printer_evaluator(input, name=None, **kwargs):
    def tr(v):
        _, idx = fluid.layers.topk(v, k=1)
        return idx

    return _printer('maxid_printer', [input], name, transform=tr)


def maxframe_printer_evaluator(input, name=None, **kwargs):
    def tr(v):
        return fluid.layers.sequence_pool(v, pool_type='max')

    return _printer('maxframe_printer', [input], name, transform=tr)


def seqtext_printer_evaluator(input, result_file=None, name=None,
                              **kwargs):
    if result_file is not None:
        import warnings
        warnings.warn(
            'seqtext_printer_evaluator: result_file is not supported '
            '(documented delta) - sequences print to stdout via the '
            'print host op instead of writing %r' % result_file)
    return _printer('seqtext_printer', [input], name)


def classification_error_printer_evaluator(input, label, name=None,
                                           **kwargs):
    def tr(iv, lv):
        acc = fluid.layers.accuracy(input=iv, label=lv)
        return fluid.layers.scale(acc, scale=-1.0, bias=1.0)

    return _printer('classification_error_printer', [input, label], name,
                    transform=tr)
