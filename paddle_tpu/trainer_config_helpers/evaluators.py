"""Legacy evaluator DSL (reference
trainer_config_helpers/evaluators.py:18 — evaluators attach metric
computations to a config's output layers).

Each evaluator returns a v2 DAG node computing the metric through the
fluid metric ops (layers/metric_op.py), so legacy configs that attach
evaluators get real, fetchable metric values from the same compiled
program."""

from ..v2 import layer as _v2
from .. import fluid

__all__ = [
    'classification_error_evaluator', 'auc_evaluator',
    'ctc_error_evaluator', 'chunk_evaluator', 'sum_evaluator',
    'column_sum_evaluator',
]


def _metric_layer(kind, parents, build, name):
    layer = _v2.Layer(kind, parents, build, name=name)
    layer.is_evaluator = True
    return layer


def classification_error_evaluator(input, label, name=None, **kwargs):
    """Error rate = 1 - accuracy (reference evaluators.py:220)."""

    def build(ctx, input_var, label_var):
        acc = fluid.layers.accuracy(input=input_var, label=label_var)
        return fluid.layers.scale(acc, scale=-1.0, bias=1.0)

    return _metric_layer('classification_error', [input, label], build,
                         name)


def auc_evaluator(input, label, name=None, **kwargs):
    """(reference evaluators.py:272)"""

    def build(ctx, input_var, label_var):
        auc_out, _, _ = fluid.layers.auc(input=input_var, label=label_var)
        return auc_out

    return _metric_layer('auc', [input, label], build, name)


def ctc_error_evaluator(input, label, name=None, **kwargs):
    """Edit-distance between CTC decodes and labels
    (reference evaluators.py:398)."""

    def build(ctx, input_var, label_var):
        decoded = fluid.layers.ctc_greedy_decoder(input=input_var,
                                                  blank=0)
        dist, _ = fluid.layers.edit_distance(decoded, label_var)
        return fluid.layers.mean(dist)

    return _metric_layer('ctc_error', [input, label], build, name)


def chunk_evaluator(input, label, chunk_scheme='IOB', num_chunk_types=1,
                    name=None, **kwargs):
    """Chunk F1 (reference evaluators.py:425)."""

    def build(ctx, input_var, label_var):
        _, _, f1, _, _, _ = fluid.layers.chunk_eval(
            input=input_var, label=label_var,
            chunk_scheme=chunk_scheme.lower(),
            num_chunk_types=num_chunk_types)
        return f1

    return _metric_layer('chunk_f1', [input, label], build, name)


def sum_evaluator(input, name=None, **kwargs):
    def build(ctx, input_var):
        return fluid.layers.reduce_sum(input_var)

    return _metric_layer('sum', [input], build, name)


def column_sum_evaluator(input, name=None, **kwargs):
    def build(ctx, input_var):
        return fluid.layers.reduce_sum(input_var, dim=0)

    return _metric_layer('column_sum', [input], build, name)
