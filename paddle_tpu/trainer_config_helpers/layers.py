"""Legacy layer builders (reference trainer_config_helpers/layers.py —
6457 LoC, ~100 builders; this file carries the ~70 most-used ones).

Each ``*_layer`` returns a v2 DAG node (paddle_tpu.v2.layer.Layer); the
legacy names and calling conventions are preserved, the engine is the
TPU fluid stack.  ``outputs()`` records the config's roots the way the
old parser did (config_parser marks output layers)."""

from ..v2 import layer as _v2
from ..v2 import data_type as _dt

__all__ = [
    # io / core
    'data_layer', 'fc_layer', 'embedding_layer', 'img_conv_layer',
    'img_pool_layer', 'pooling_layer', 'concat_layer', 'addto_layer',
    'dropout_layer', 'lstmemory', 'grumemory', 'batch_norm_layer',
    'last_seq', 'first_seq', 'maxid_layer', 'memory', 'recurrent_group',
    'StaticInput', 'outputs', 'get_config', 'reset_config',
    # elementwise / shape
    'trans_layer', 'scaling_layer', 'slope_intercept_layer', 'clip_layer',
    'pad_layer', 'rotate_layer', 'repeat_layer', 'interpolation_layer',
    'power_layer', 'sum_to_one_norm_layer', 'bilinear_interp_layer',
    'img_cmrnorm_layer', 'maxout_layer',
    # sequence
    'expand_layer', 'seq_concat_layer', 'seq_reshape_layer',
    'block_expand_layer', 'row_conv_layer', 'gru_step_layer',
    'lstm_step_layer', 'eos_layer',
    # similarity / products
    'cos_sim', 'dot_prod_layer', 'out_prod_layer', 'l2_distance_layer',
    'multiplex_layer', 'sampling_id_layer', 'print_layer',
    'selective_fc_layer', 'get_output_layer',
    # second tail batch
    'prelu_layer', 'crop_layer', 'sub_seq_layer', 'kmax_seq_score_layer',
    'linear_comb_layer', 'convex_comb_layer', 'tensor_layer',
    'conv_shift_layer', 'scale_shift_layer', 'gated_unit_layer',
    'roi_pool_layer', 'priorbox_layer', 'cross_channel_norm_layer',
    # third tail batch
    'resize_layer', 'row_l2_norm_layer', 'switch_order_layer',
    'upsample_layer', 'spp_layer', 'recurrent_layer',
    'img_conv3d_layer', 'img_pool3d_layer', 'factorization_machine',
    'scaling_projection', 'slice_projection', 'dotmul_operator',
    'conv_operator', 'detection_output_layer', 'multibox_loss_layer',
    'scale_sub_region_layer', 'square_error_cost',
    'printer_layer', 'gru_step_naive_layer', 'seq_slice_layer',
    'layer_support',
    # mixed + projections
    'mixed_layer', 'full_matrix_projection',
    'trans_full_matrix_projection', 'identity_projection',
    'table_projection', 'dotmul_projection', 'context_projection',
    'conv_projection',
    # costs
    'classification_cost', 'cross_entropy', 'regression_cost', 'mse_cost',
    'rank_cost', 'smooth_l1_cost', 'multi_binary_label_cross_entropy',
    'crf_layer', 'crf_decoding_layer', 'ctc_layer', 'warp_ctc_layer',
    'hsigmoid', 'nce_layer', 'sum_cost', 'huber_regression_cost',
    'huber_classification_cost', 'lambda_cost', 'cross_entropy_with_selfnorm',
    # round-4: the last three builders (108/108, VERDICT r3 next-#4)
    'sub_nested_seq_layer', 'BeamInput', 'cross_entropy_over_beam',
    'beam_search', 'GeneratedInput', 'AggregateLevel', 'ExpandLevel',
]

_OUTPUTS = []


def data_layer(name, size, data_type_kind='dense', seq=False, **kwargs):
    """(reference layers.py data_layer).  The legacy DSL declares only
    name+size; the value kind rides ``data_type_kind``:
    'dense'|'index', seq=True for sequence input, seq='sub' for a
    nested (SUB_SEQUENCE) input."""
    nested = seq in ('sub', 'nested', 2)
    if data_type_kind == 'index':
        t = (_dt.integer_value_sub_sequence(size) if nested else
             _dt.integer_value_sequence(size) if seq else
             _dt.integer_value(size))
    else:
        t = (_dt.dense_vector_sub_sequence(size) if nested else
             _dt.dense_vector_sequence(size) if seq else
             _dt.dense_vector(size))
    return _v2.data(name=name, type=t)


def _with_layer_attr(layer, kwargs):
    """Apply the semantic half of ExtraLayerAttribute: ``drop_rate``
    wraps the built layer in dropout (the reference config_parser's
    post-layer dropout insertion).  The placement/engine knobs (device,
    error_clipping_threshold) have no per-layer XLA analog — see the
    PARITY.md fidelity audit."""
    la = kwargs.get('layer_attr')
    dr = getattr(la, 'drop_rate', None) if la is not None else None
    if dr:
        wrapped = _v2.dropout(input=layer, dropout_rate=dr)
        # the user-facing layer NAME must resolve to the post-dropout
        # value — the legacy config_parser applies drop_rate on the
        # named layer itself, so memory(name=...) links and downstream
        # name lookups see the dropped output
        wrapped.name, layer.name = layer.name, wrapped.name
        return wrapped
    return layer


def fc_layer(input, size, act=None, name=None, param_attr=None,
             bias_attr=None, **kwargs):
    return _with_layer_attr(
        _v2.fc(input=input, size=size, act=act, name=name,
               param_attr=param_attr, bias_attr=bias_attr), kwargs)


def embedding_layer(input, size, name=None, param_attr=None, **kwargs):
    return _v2.embedding(input=input, size=size, name=name,
                         param_attr=param_attr)


def img_conv_layer(input, filter_size, num_filters, num_channels=None,
                   stride=1, padding=0, act=None, name=None,
                   param_attr=None, bias_attr=None, **kwargs):
    return _with_layer_attr(
        _v2.img_conv(input=input, filter_size=filter_size,
                     num_filters=num_filters,
                     num_channels=num_channels, stride=stride,
                     padding=padding, act=act, name=name,
                     param_attr=param_attr, bias_attr=bias_attr), kwargs)


def img_pool_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                   name=None, **kwargs):
    return _v2.img_pool(input=input, pool_size=pool_size, stride=stride,
                        padding=padding, pool_type=pool_type, name=name)


AggregateLevel = _v2.AggregateLevel


def pooling_layer(input, pooling_type=None, name=None,
                  agg_level=AggregateLevel.TO_NO_SEQUENCE, **kwargs):
    return _v2.pooling(input=input, pooling_type=pooling_type, name=name,
                       agg_level=agg_level)


def concat_layer(input, name=None, **kwargs):
    return _v2.concat(input=input, name=name)


def addto_layer(input, act=None, name=None, **kwargs):
    return _v2.addto(input=input, act=act, name=name)


def dropout_layer(input, dropout_rate, name=None, **kwargs):
    return _v2.dropout(input=input, dropout_rate=dropout_rate, name=name)


def lstmemory(input, size=None, name=None, reverse=False, param_attr=None,
              bias_attr=None, **kwargs):
    return _with_layer_attr(
        _v2.lstmemory(input=input, size=size, name=name,
                      reverse=reverse, param_attr=param_attr,
                      bias_attr=bias_attr), kwargs)


def grumemory(input, size, name=None, reverse=False, param_attr=None,
              bias_attr=None, project=None, **kwargs):
    return _with_layer_attr(
        _v2.gru_like(input=input, size=size, name=name,
                     reverse=reverse, param_attr=param_attr,
                     bias_attr=bias_attr, project=project), kwargs)


def batch_norm_layer(input, act=None, name=None, epsilon=1e-5,
                     moving_average_fraction=0.9, use_global_stats=None,
                     param_attr=None, bias_attr=None, **kwargs):
    return _with_layer_attr(
        _v2.batch_norm(input=input, act=act, name=name, epsilon=epsilon,
                       moving_average_fraction=moving_average_fraction,
                       use_global_stats=use_global_stats,
                       param_attr=param_attr, bias_attr=bias_attr),
        kwargs)


def last_seq(input, name=None,
             agg_level=AggregateLevel.TO_NO_SEQUENCE, **kwargs):
    return _v2.last_seq(input=input, name=name, agg_level=agg_level)


def first_seq(input, name=None,
              agg_level=AggregateLevel.TO_NO_SEQUENCE, **kwargs):
    return _v2.first_seq(input=input, name=name, agg_level=agg_level)


def maxid_layer(input, name=None, **kwargs):
    return _v2.max_id(input=input, name=name)


memory = _v2.memory
recurrent_group = _v2.recurrent_group
beam_search = _v2.beam_search
GeneratedInput = _v2.GeneratedInput
BaseGeneratedInput = _v2.BaseGeneratedInput
StaticInput = _v2.StaticInput


# ---- elementwise / shape ----
def trans_layer(input, name=None, **kwargs):
    return _v2.trans(input=input, name=name)


def scaling_layer(input, weight, name=None, **kwargs):
    return _v2.scaling(input=input, weight=weight, name=name)


def slope_intercept_layer(input, slope=1.0, intercept=0.0, name=None,
                          **kwargs):
    return _v2.slope_intercept(input=input, slope=slope,
                               intercept=intercept, name=name)


def clip_layer(input, min, max, name=None, **kwargs):
    return _v2.clip(input=input, min=min, max=max, name=name)


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, name=None,
              **kwargs):
    return _v2.pad(input=input, pad_c=pad_c, pad_h=pad_h, pad_w=pad_w,
                   name=name)


def rotate_layer(input, height, width, name=None, **kwargs):
    return _v2.rotate(input=input, height=height, width=width, name=name)


def repeat_layer(input, num_repeats, name=None, **kwargs):
    return _v2.repeat(input=input, num_repeats=num_repeats, name=name)


def interpolation_layer(input, weight, name=None, **kwargs):
    return _v2.interpolation(input=input, weight=weight, name=name)


def power_layer(input, weight, name=None, **kwargs):
    return _v2.power(input=input, weight=weight, name=name)


def sum_to_one_norm_layer(input, name=None, **kwargs):
    return _v2.sum_to_one_norm(input=input, name=name)


def bilinear_interp_layer(input, out_size_x, out_size_y, name=None,
                          **kwargs):
    return _v2.bilinear_interp(input=input, out_size_x=out_size_x,
                               out_size_y=out_size_y, name=name)


def img_cmrnorm_layer(input, size=5, scale=0.0001, power=0.75, name=None,
                      **kwargs):
    return _v2.img_cmrnorm(input=input, size=size, scale=scale,
                           power=power, name=name)


def maxout_layer(input, groups, name=None, **kwargs):
    return _v2.maxout(input=input, groups=groups, name=name)


# ---- sequence ----
ExpandLevel = _v2.ExpandLevel


def expand_layer(input, expand_as, name=None,
                 expand_level=ExpandLevel.FROM_NO_SEQUENCE, **kwargs):
    return _v2.expand(input=input, expand_as=expand_as, name=name,
                      expand_level=expand_level)


def seq_concat_layer(a, b, name=None, **kwargs):
    return _v2.seq_concat(a=a, b=b, name=name)


def seq_reshape_layer(input, reshape_size, name=None, **kwargs):
    return _v2.seq_reshape(input=input, reshape_size=reshape_size,
                           name=name)


def block_expand_layer(input, block_x, block_y, stride_x=1, stride_y=1,
                       padding_x=0, padding_y=0, name=None, **kwargs):
    return _v2.block_expand(input=input, block_x=block_x, block_y=block_y,
                            stride_x=stride_x, stride_y=stride_y,
                            padding_x=padding_x, padding_y=padding_y,
                            name=name)


def row_conv_layer(input, context_len, name=None, **kwargs):
    return _v2.row_conv(input=input, context_len=context_len, name=name)


def gru_step_layer(input, output_mem, size=None, act=None, gate_act=None,
                   name=None, **kwargs):
    return _v2.gru_step(input=input, state=output_mem,
                        size=size or output_mem.size, act=act,
                        gate_act=gate_act, name=name)


def lstm_step_layer(input, state, cell, size=None, act=None,
                    gate_act=None, name=None, **kwargs):
    return _v2.lstm_step(input=input, state=state, cell=cell,
                         size=size or state.size, act=act,
                         gate_act=gate_act, name=name)


def eos_layer(input, eos_id, name=None, **kwargs):
    """1.0 where the id equals eos_id (reference eos_layer)."""

    def build(ctx, v):
        from .. import fluid
        eos = fluid.layers.fill_constant_batch_size_like(
            v, shape=[-1, 1], value=float(eos_id), dtype='int64')
        return fluid.layers.cast(fluid.layers.equal(v, eos), 'float32')

    return _v2.Layer('eos', [input], build, name=name, size=1)


# ---- similarity / products / misc ----
def cos_sim(a, b, scale=1.0, name=None, **kwargs):
    return _v2.cos_sim(a=a, b=b, scale=scale, name=name)


def dot_prod_layer(a, b, name=None, **kwargs):
    return _v2.dot_prod(a=a, b=b, name=name)


def out_prod_layer(a, b, name=None, **kwargs):
    return _v2.out_prod(a=a, b=b, name=name)


def l2_distance_layer(a, b, name=None, **kwargs):
    return _v2.l2_distance(a=a, b=b, name=name)


def multiplex_layer(input, name=None, **kwargs):
    return _v2.multiplex(input=input, name=name)


def sampling_id_layer(input, name=None, **kwargs):
    return _v2.sampling_id(input=input, name=name)


def print_layer(input, message=None, name=None, **kwargs):
    return _v2.print_layer(input=input, message=message, name=name)


def selective_fc_layer(input, size, act=None, name=None, **kwargs):
    """Reference selective_fc computes only selected columns; the dense
    fc is numerically identical on the full column set (selection was a
    legacy-CPU speed trick)."""
    return _v2.fc(input=input, size=size, act=act, name=name)


def get_output_layer(input, arg_name=None, name=None, **kwargs):
    """Reference get_output_layer exposes a named auxiliary output of a
    layer (e.g. the lstm cell state); aux outputs are materialized into
    the build ctx under '<layer>@<arg>'."""

    def build(ctx, v):
        if not arg_name:
            return v
        key = '%s@%s' % (input.name, arg_name)
        if key not in ctx:
            raise KeyError(
                'get_output_layer: layer %r publishes no output %r '
                '(known aux keys: %s)' %
                (input.name, arg_name,
                 [k for k in ctx if '@' in str(k)]))
        return ctx[key]

    return _v2.Layer('get_output', [input], build, name=name,
                     size=input.size)


def prelu_layer(input, name=None, **kwargs):
    return _v2.prelu(input=input, name=name)


def crop_layer(input, shape=None, offsets=None, name=None, **kwargs):
    return _v2.crop(input=input, shape=shape, offsets=offsets, name=name)


def sub_seq_layer(input, starts, ends, name=None, **kwargs):
    return _v2.sub_seq(input=input, starts=starts, ends=ends, name=name)


def sub_nested_seq_layer(input, selected_indices, name=None, **kwargs):
    return _v2.sub_nested_seq(input=input,
                              selected_indices=selected_indices, name=name)


BeamInput = _v2.BeamInput


def cross_entropy_over_beam(input, name=None, **kwargs):
    return _v2.cross_entropy_over_beam(input=input, name=name)


def kmax_seq_score_layer(input, beam_size=1, name=None, **kwargs):
    return _v2.kmax_seq_score(input=input, beam_size=beam_size, name=name)


def linear_comb_layer(weights, vectors, size=None, name=None, **kwargs):
    return _v2.linear_comb(weights=weights, vectors=vectors, size=size,
                           name=name)


convex_comb_layer = linear_comb_layer


def tensor_layer(a, b, size, name=None, **kwargs):
    return _v2.tensor_product(a=a, b=b, size=size, name=name)


def conv_shift_layer(a, b, name=None, **kwargs):
    return _v2.conv_shift(a=a, b=b, name=name)


def scale_shift_layer(input, name=None, **kwargs):
    return _v2.scale_shift(input=input, name=name)


def gated_unit_layer(input, size, name=None, **kwargs):
    return _v2.gated_unit(input=input, size=size, name=name)


def roi_pool_layer(input, rois, pooled_width, pooled_height,
                   spatial_scale=1.0, name=None, **kwargs):
    return _v2.roi_pool(input=input, rois=rois,
                        pooled_width=pooled_width,
                        pooled_height=pooled_height,
                        spatial_scale=spatial_scale, name=name)


def priorbox_layer(input, image, min_size, max_size=None,
                   aspect_ratio=None, variance=None, num_channels=3,
                   name=None, **kwargs):
    return _v2.priorbox(input=input, image=image, min_sizes=min_size,
                        max_sizes=max_size, aspect_ratios=aspect_ratio,
                        variance=variance, num_channels=num_channels,
                        name=name)


def cross_channel_norm_layer(input, num_channels=None, name=None,
                             **kwargs):
    return _v2.cross_channel_norm(input=input, num_channels=num_channels,
                                  name=name)


def resize_layer(input, size, name=None, **kwargs):
    return _v2.resize(input=input, size=size, name=name)


def row_l2_norm_layer(input, name=None, **kwargs):
    return _v2.row_l2_norm(input=input, name=name)


def switch_order_layer(input, reshape_from='NCHW', reshape_to='NHWC',
                       name=None, **kwargs):
    return _v2.switch_order(input=input, reshape_from=reshape_from,
                            reshape_to=reshape_to, name=name)


def upsample_layer(input, scale=2, upsample_mode='nearest', name=None,
                   **kwargs):
    return _v2.upsample(input=input, scale=scale,
                        upsample_mode=upsample_mode, name=name)


def spp_layer(input, pyramid_height=2, pool_type=None, name=None,
              **kwargs):
    return _v2.spp(input=input, pyramid_height=pyramid_height,
                   pool_type=pool_type, name=name)


def recurrent_layer(input, size=None, act=None, reverse=False,
                    name=None, param_attr=None, bias_attr=None, **kwargs):
    return _v2.recurrent(input=input, size=size, act=act,
                         reverse=reverse, name=name,
                         param_attr=param_attr, bias_attr=bias_attr)


def img_conv3d_layer(input, filter_size, num_filters, num_channels=None,
                     stride=1, padding=0, act=None, name=None, **kwargs):
    return _v2.img_conv3d(input=input, filter_size=filter_size,
                          num_filters=num_filters,
                          num_channels=num_channels, stride=stride,
                          padding=padding, act=act, name=name)


def img_pool3d_layer(input, pool_size, stride=1, padding=0,
                     pool_type=None, name=None, **kwargs):
    return _v2.img_pool3d(input=input, pool_size=pool_size,
                          stride=stride, padding=padding,
                          pool_type=pool_type, name=name)


factorization_machine = _v2.factorization_machine
scaling_projection = _v2.scaling_projection
slice_projection = _v2.slice_projection
dotmul_operator = _v2.dotmul_operator
conv_operator = _v2.conv_operator


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, name=None, **kwargs):
    return _v2.detection_output(loc=input_loc, conf=input_conf,
                                priorbox_layer_out=priorbox,
                                num_classes=num_classes,
                                nms_threshold=nms_threshold, name=name)


def multibox_loss_layer(input_loc, input_conf, priorbox, label,
                        num_classes, name=None, **kwargs):
    """SSD multibox training loss (reference multibox_loss_layer ->
    fluid ssd_loss).  ``label`` carries the legacy combined ground
    truth: [class, xmin, ymin, xmax, ymax] per row; the wrapper splits
    it into the gt_label / gt_box pair ssd_loss takes and reshapes flat
    conv outputs to [N, P, 4] / [N, P, C]."""
    from .. import fluid

    def build(ctx, loc_v, conf_v, pb_v, lbl_v):
        variances = ctx.get('%s@variances' % priorbox.name)
        if len(loc_v.shape) == 2:
            loc_v = fluid.layers.reshape(loc_v, shape=[0, -1, 4])
        if len(conf_v.shape) == 2:
            conf_v = fluid.layers.reshape(
                conf_v, shape=[0, -1, int(num_classes)])
        gt_label = fluid.layers.cast(
            fluid.layers.slice(lbl_v, axes=[1], starts=[0], ends=[1]),
            'int64')
        gt_box = fluid.layers.slice(lbl_v, axes=[1], starts=[1],
                                    ends=[5])
        loss = fluid.layers.ssd_loss(
            loc_v, conf_v, gt_box, gt_label, pb_v, variances)
        return fluid.layers.mean(loss)

    layer = _v2.Layer('multibox_loss',
                      [input_loc, input_conf, priorbox, label], build,
                      name=name)
    layer.is_cost = True
    return layer


def layer_support(*attrs):
    """(reference layers.py layer_support decorator) — attribute
    plumbing is handled per-builder here; kept as an identity decorator
    so configs importing it keep working."""

    def decorate(fn):
        return fn

    return decorate


# ---- mixed + projections ----
mixed_layer = _v2.mixed
full_matrix_projection = _v2.full_matrix_projection
trans_full_matrix_projection = _v2.trans_full_matrix_projection
identity_projection = _v2.identity_projection
table_projection = _v2.table_projection
dotmul_projection = _v2.dotmul_projection
context_projection = _v2.context_projection
conv_projection = _v2.conv_projection


# ---- costs ----
def classification_cost(input, label, name=None, **kwargs):
    return _v2.classification_cost(input=input, label=label, name=name)


def cross_entropy(input, label, name=None, **kwargs):
    return _v2.cross_entropy_cost(input=input, label=label, name=name)


def regression_cost(input, label, name=None, **kwargs):
    return _v2.square_error_cost(input=input, label=label, name=name)


mse_cost = regression_cost


def rank_cost(left, right, label, name=None, **kwargs):
    return _v2.rank_cost(left=left, right=right, label=label, name=name)


def smooth_l1_cost(input, label, name=None, **kwargs):
    return _v2.smooth_l1_cost(input=input, label=label, name=name)


def multi_binary_label_cross_entropy(input, label, name=None, **kwargs):
    return _v2.multi_binary_label_cross_entropy_cost(
        input=input, label=label, name=name)


def crf_layer(input, label, size=None, name=None, **kwargs):
    return _v2.crf(input=input, label=label, size=size, name=name)


def crf_decoding_layer(input, size=None, label=None, name=None, **kwargs):
    return _v2.crf_decoding(input=input, size=size, label=label,
                            name=name)


def ctc_layer(input, label, size=None, blank=0, norm_by_times=False,
              name=None, **kwargs):
    return _v2.ctc(input=input, label=label, size=size, blank=blank,
                   norm_by_times=norm_by_times, name=name)


# the reference's warp_ctc_layer is the same contract via the warp-ctc
# library; here both lower to the one native CTC loss
warp_ctc_layer = ctc_layer


def hsigmoid(input, label, num_classes, name=None, **kwargs):
    return _v2.hsigmoid(input=input, label=label,
                        num_classes=num_classes, name=name)


def nce_layer(input, label, num_classes, num_neg_samples=10, name=None,
              **kwargs):
    return _v2.nce(input=input, label=label, num_classes=num_classes,
                   num_neg_samples=num_neg_samples, name=name)


def sum_cost(input, name=None, **kwargs):
    return _v2.sum_cost(input=input, name=name)


def huber_regression_cost(input, label, delta=1.0, name=None, **kwargs):
    return _v2.huber_regression_cost(input=input, label=label,
                                     delta=delta, name=name)


def huber_classification_cost(input, label, name=None, **kwargs):
    return _v2.huber_classification_cost(input=input, label=label,
                                         name=name)


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, name=None,
                **kwargs):
    """LambdaRank cost (reference lambda_cost) as a trainable pairwise
    surrogate: each list position is paired with its time-reversed
    counterpart and trained with the RankNet loss under the relevance
    ordering from ``score`` — a documented simplification of the
    reference's NDCG-weighted pair enumeration (the gradients push the
    same orderings; the NDCG weights are dropped)."""
    from .. import fluid

    def build(ctx, iv, sv):
        rev_i = fluid.layers.reverse(iv, axis=1)
        rev_s = fluid.layers.reverse(sv, axis=1)
        lbl = fluid.layers.cast(
            fluid.layers.less_than(rev_s, sv), 'float32')
        return fluid.layers.mean(
            fluid.layers.rank_loss(lbl, iv, rev_i))

    layer = _v2.Layer('lambda_cost', [input, score], build, name=name)
    layer.is_cost = True
    layer.prediction_parent = input
    return layer


def cross_entropy_with_selfnorm(input, label, softmax_selfnorm_alpha=0.1,
                                name=None, **kwargs):
    """CE + alpha * log(Z)^2 self-normalization (reference
    CrossEntropyOverBeam sibling cost).  ``input`` must be the
    UN-normalized score layer (a plain fc, no softmax): the layer
    computes the softmax itself so the normalizer Z = sum(exp(scores))
    exists to penalize — on an already-softmaxed input Z == 1 and the
    penalty would vanish, which is why the reference config also feeds
    raw scores here."""
    from .. import fluid

    def build(ctx, iv, lv):
        pred = fluid.layers.softmax(iv)
        ce = fluid.layers.cross_entropy(input=pred, label=lv)
        z = fluid.layers.reduce_sum(fluid.layers.exp(iv), dim=1,
                                    keep_dim=True)
        logz = fluid.layers.log(z)
        pen = fluid.layers.scale(
            fluid.layers.elementwise_mul(logz, logz),
            scale=float(softmax_selfnorm_alpha))
        return fluid.layers.mean(
            fluid.layers.elementwise_add(ce, pen))

    layer = _v2.Layer('ce_selfnorm', [input, label], build, name=name)
    layer.is_cost = True
    layer.prediction_parent = input
    return layer


def outputs(*layers):
    """(reference config_parser outputs()): mark the config's roots."""
    _OUTPUTS.extend(layers)


def get_config():
    """The executable view of the parsed config: (output/cost layers,
    settings dict) — what the legacy trainer binary extracted from the
    protobuf ModelConfig."""
    from .optimizers import get_settings
    return list(_OUTPUTS), get_settings()


def reset_config():
    from .optimizers import _SETTINGS
    from .data_sources import reset_data_sources
    del _OUTPUTS[:]
    _SETTINGS.clear()  # a new config must not inherit old hyperparams
    reset_data_sources()


# reference aliases (targets defined above)
square_error_cost = regression_cost
printer_layer = print_layer
gru_step_naive_layer = gru_step_layer
seq_slice_layer = sub_seq_layer


def scale_sub_region_layer(input, indices, value=1.0, num_channels=None,
                           name=None, **kwargs):
    return _v2.scale_sub_region(input=input, indices=indices,
                                value=value, num_channels=num_channels,
                                name=name)
