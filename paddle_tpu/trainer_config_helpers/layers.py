"""Legacy layer builders (reference trainer_config_helpers/layers.py).

Each ``*_layer`` returns a v2 DAG node (paddle_tpu.v2.layer.Layer); the
legacy names and calling conventions are preserved, the engine is the
TPU fluid stack.  ``outputs()`` records the config's roots the way the
old parser did (config_parser marks output layers)."""

from ..v2 import layer as _v2
from ..v2 import data_type as _dt

__all__ = [
    'data_layer', 'fc_layer', 'embedding_layer', 'img_conv_layer',
    'img_pool_layer', 'pooling_layer', 'concat_layer', 'addto_layer',
    'dropout_layer', 'lstmemory', 'grumemory', 'batch_norm_layer',
    'last_seq', 'first_seq', 'maxid_layer', 'memory', 'recurrent_group',
    'StaticInput', 'classification_cost', 'cross_entropy',
    'regression_cost', 'mse_cost', 'rank_cost', 'smooth_l1_cost',
    'multi_binary_label_cross_entropy', 'outputs', 'get_config',
    'reset_config',
]

_OUTPUTS = []


def data_layer(name, size, data_type_kind='dense', seq=False, **kwargs):
    """(reference layers.py data_layer).  The legacy DSL declares only
    name+size; the value kind rides ``data_type_kind``:
    'dense'|'index', seq=True for sequence input."""
    if data_type_kind == 'index':
        t = _dt.integer_value_sequence(size) if seq else \
            _dt.integer_value(size)
    else:
        t = _dt.dense_vector_sequence(size) if seq else \
            _dt.dense_vector(size)
    return _v2.data(name=name, type=t)


def fc_layer(input, size, act=None, name=None, param_attr=None,
             bias_attr=None, **kwargs):
    return _v2.fc(input=input, size=size, act=act, name=name)


def embedding_layer(input, size, name=None, param_attr=None, **kwargs):
    return _v2.embedding(input=input, size=size, name=name)


def img_conv_layer(input, filter_size, num_filters, num_channels=None,
                   stride=1, padding=0, act=None, name=None, **kwargs):
    return _v2.img_conv(input=input, filter_size=filter_size,
                        num_filters=num_filters,
                        num_channels=num_channels, stride=stride,
                        padding=padding, act=act, name=name)


def img_pool_layer(input, pool_size, stride=1, padding=0, pool_type=None,
                   name=None, **kwargs):
    return _v2.img_pool(input=input, pool_size=pool_size, stride=stride,
                        padding=padding, pool_type=pool_type, name=name)


def pooling_layer(input, pooling_type=None, name=None, **kwargs):
    return _v2.pooling(input=input, pooling_type=pooling_type, name=name)


def concat_layer(input, name=None, **kwargs):
    return _v2.concat(input=input, name=name)


def addto_layer(input, act=None, name=None, **kwargs):
    return _v2.addto(input=input, act=act, name=name)


def dropout_layer(input, dropout_rate, name=None, **kwargs):
    return _v2.dropout(input=input, dropout_rate=dropout_rate, name=name)


def lstmemory(input, size=None, name=None, reverse=False, **kwargs):
    return _v2.lstmemory(input=input, size=size, name=name)


def grumemory(input, size, name=None, **kwargs):
    return _v2.gru_like(input=input, size=size, name=name)


def batch_norm_layer(input, act=None, name=None, **kwargs):
    return _v2.batch_norm(input=input, act=act, name=name)


def last_seq(input, name=None, **kwargs):
    return _v2.last_seq(input=input, name=name)


def first_seq(input, name=None, **kwargs):
    return _v2.first_seq(input=input, name=name)


def maxid_layer(input, name=None, **kwargs):
    return _v2.max_id(input=input, name=name)


memory = _v2.memory
recurrent_group = _v2.recurrent_group
StaticInput = _v2.StaticInput


def classification_cost(input, label, name=None, **kwargs):
    return _v2.classification_cost(input=input, label=label, name=name)


def cross_entropy(input, label, name=None, **kwargs):
    return _v2.cross_entropy_cost(input=input, label=label, name=name)


def regression_cost(input, label, name=None, **kwargs):
    return _v2.square_error_cost(input=input, label=label, name=name)


mse_cost = regression_cost


def rank_cost(left, right, label, name=None, **kwargs):
    return _v2.rank_cost(left=left, right=right, label=label, name=name)


def smooth_l1_cost(input, label, name=None, **kwargs):
    return _v2.smooth_l1_cost(input=input, label=label, name=name)


def multi_binary_label_cross_entropy(input, label, name=None, **kwargs):
    return _v2.multi_binary_label_cross_entropy_cost(
        input=input, label=label, name=name)


def outputs(*layers):
    """(reference config_parser outputs()): mark the config's roots."""
    _OUTPUTS.extend(layers)


def get_config():
    """The executable view of the parsed config: (output/cost layers,
    settings dict) — what the legacy trainer binary extracted from the
    protobuf ModelConfig."""
    from .optimizers import get_settings
    return list(_OUTPUTS), get_settings()


def reset_config():
    from .optimizers import _SETTINGS
    del _OUTPUTS[:]
    _SETTINGS.clear()  # a new config must not inherit old hyperparams
