"""Legacy pooling objects (reference trainer_config_helpers/poolings.py)."""

from ..v2 import pooling as _pooling

__all__ = ['MaxPooling', 'AvgPooling', 'SumPooling']

MaxPooling = _pooling.Max
AvgPooling = _pooling.Avg
SumPooling = _pooling.Sum
