"""Legacy (pre-v2) config DSL (reference:
python/paddle/trainer_config_helpers/__init__.py).

The reference's oldest API generation: model configs are Python files
calling ``settings(...)``, ``*_layer(...)`` builders and ``outputs(...)``,
parsed by the trainer binary into a protobuf ModelConfig for the legacy
GradientMachine.  Here the same surface builds the v2 DAG (itself a shim
over the TPU fluid stack), so legacy config files execute as one compiled
XLA program:

    from paddle_tpu.trainer_config_helpers import *
    settings(batch_size=32, learning_rate=1e-3,
             learning_method=AdamOptimizer())
    x = data_layer(name='x', size=16)
    h = fc_layer(input=x, size=32, act=TanhActivation())
    y = fc_layer(input=h, size=4, act=SoftmaxActivation())
    lbl = data_layer(name='label', size=4, data_type_kind='index')
    outputs(classification_cost(input=y, label=lbl))

``get_config()`` then hands (costs, settings) to the v2 trainer flow.
"""

from .activations import *  # noqa: F401,F403
from .data_sources import *  # noqa: F401,F403
from .poolings import *  # noqa: F401,F403
from .attrs import *  # noqa: F401,F403
from .optimizers import *  # noqa: F401,F403
from .layers import *  # noqa: F401,F403
from .networks import *  # noqa: F401,F403
from .evaluators import *  # noqa: F401,F403

from . import activations, poolings, attrs, optimizers, layers, \
    networks, evaluators, data_sources

__all__ = (activations.__all__ + poolings.__all__ + attrs.__all__ +
           optimizers.__all__ + layers.__all__ + networks.__all__ +
           evaluators.__all__ + data_sources.__all__)
