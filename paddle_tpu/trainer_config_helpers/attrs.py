"""Legacy attribute bags (reference trainer_config_helpers/attrs.py).
Accepted for config compatibility; placement/regularization decisions
belong to the XLA stack."""

__all__ = ['ParamAttr', 'ParameterAttribute', 'ExtraAttr',
           'ExtraLayerAttribute', 'HookAttr', 'HookAttribute']


class HookAttribute(object):
    """Parameter hook config (reference attrs.py:59 — pruning masks
    etc.).  Recorded for config compatibility; static mask pruning has
    no training-time effect under XLA's dense kernels, so hooks are
    carried as inert metadata (documented delta)."""

    def __init__(self, type, sparsity_ratio=None, **kwargs):
        self.type = type
        self.sparsity_ratio = sparsity_ratio
        if sparsity_ratio is not None and not 0.0 <= sparsity_ratio <= 1.0:
            raise ValueError('sparsity_ratio must be within [0, 1]')


HookAttr = HookAttribute


class ParameterAttribute(object):
    def __init__(self, name=None, initial_std=None, initial_mean=None,
                 learning_rate=None, l1_rate=None, l2_rate=None,
                 sparse_update=False, **kwargs):
        self.name = name
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.learning_rate = learning_rate
        self.sparse_update = sparse_update


class ExtraLayerAttribute(object):
    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None, **kwargs):
        self.drop_rate = drop_rate


ParamAttr = ParameterAttribute
ExtraAttr = ExtraLayerAttribute
