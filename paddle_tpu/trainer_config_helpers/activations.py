"""Legacy activation objects (reference
trainer_config_helpers/activations.py) — aliases of the v2 objects."""

from ..v2 import activation as _act

__all__ = [
    'TanhActivation', 'SigmoidActivation', 'SoftmaxActivation',
    'ReluActivation', 'LinearActivation', 'IdentityActivation',
]

TanhActivation = _act.Tanh
SigmoidActivation = _act.Sigmoid
SoftmaxActivation = _act.Softmax
ReluActivation = _act.Relu
LinearActivation = _act.Linear
IdentityActivation = _act.Linear
