"""Request-level tracing, per-executable cost accounting, and the
flight recorder (ISSUE 6).

The stack spans five concurrent layers (micro-batcher, trailing-dim
buckets, registry/arbiter, FeedPipeline staging threads, multi-step
scan dispatch) but observability stopped at aggregate wall-clock spans
and p50/p99 — nobody could answer "where did THIS request's 40 ms go"
or "what was in flight when the worker stalled".  The reference's
profiler/timeline tooling was exactly this layer over the Executor;
this module is its TPU-native counterpart, three legs:

  1. **span contexts** — a ``TraceContext`` carries one trace id from
     the registry router / ``submit()`` across threads and layers
     (submit thread -> micro-batch queue -> worker -> drain), marking
     absolute stage boundaries so ``finalize()`` yields a per-request
     breakdown (arbitration / queue / pad / dispatch / device / trim)
     whose stages sum to the measured end-to-end latency.  The ambient
     ``attach()``/``current()`` pair hands a context across an API
     boundary (the ModelRegistry attaches before calling
     ``engine.submit``) without widening every signature.  A bounded
     span log (``record_span`` inside a ``tracing()`` window) feeds the
     Chrome trace-event exporter (tools/trace_export.py) one lane per
     thread.

  2. **cost registry** — ``analyze_cost`` AOT-lowers a jitted callable
     with abstract (ShapeDtypeStruct) twins of its real arguments and
     extracts XLA's own ``cost_analysis()`` FLOPs + ``memory_analysis``
     bytes: the per-executable ground truth that replaces hand-derived
     MFU math (bench.py) and cross-checks the HBM arbiter's accounts.
     Gated by ``FLAGS_cost_accounting`` because the AOT compile does
     NOT share the jit call's executable cache — capture costs one
     extra XLA compile per executable (amortized by the persistent
     compile cache when FLAGS_xla_compile_cache_dir is set).

  3. **flight recorder** — a bounded ring of the last N dispatch/lot
     records (trace ids, signatures, shapes, timings) that ``dump()``s
     on worker error or when the ``watchdog`` trips a registered stall
     probe (queue age / feed-stall thresholds) — the post-mortem a
     stalled serving worker otherwise takes to its grave.
"""

import contextlib
import itertools
import json
import logging
import os
import threading
import time
from collections import deque

__all__ = [
    'TraceContext', 'STAGES', 'new_trace_id', 'attach', 'current',
    'tracing', 'record_span', 'spans', 'clear_spans', 'dump_spans',
    'FlightRecorder', 'flight_recorder', 'Watchdog', 'watchdog',
    'analyze_cost',
]

# canonical per-request stages, in pipeline order: arbitration (the
# registry's residency gate, pre-enqueue), queue (enqueue -> lot
# collection), pad (request prepare + lot padding), dispatch (lot ready
# -> device dispatch issued, incl. carry/gate waits), device (dispatch
# -> host sync), trim (sync -> per-request slice delivered).
# GENERATION requests (ISSUE 7) replace the post-collection stages with
# prefill (lot -> slot admission: the prompt's pad/dispatch/device/trim
# as one stage), decode (admission -> last decode-scan sync) and
# detokenize (last sync -> delivery); their breakdown also carries a
# decode_steps count.
# SHED requests (ISSUE 8) end in a 'shed' stage instead: the seconds
# the request sat before the deadline scheduler dropped it (its future
# raises DeadlineExceededError — served stages before the shed, e.g. a
# generation's prefill, still appear).
STAGES = ('arbitration', 'queue', 'pad', 'prefill', 'dispatch',
          'device', 'trim', 'decode', 'detokenize', 'shed')

_ids = itertools.count(1)
_id_lock = threading.Lock()


def new_trace_id():
    with _id_lock:
        return 'tr-%06d' % next(_ids)


class TraceContext(object):
    """One request's trace: an id, absolute stage-boundary marks, and
    pre-accumulated stage seconds (stages measured where they happen —
    the registry's arbitration window, the submit path's prepare —
    before the boundary marks take over).  Thread-crossing is the
    point: the submit thread marks 'enqueue', the worker marks
    'collect'/'lot'/'dispatch', the drain marks 'sync', and
    ``finalize()`` (at delivery) turns the marks into the breakdown."""

    __slots__ = ('trace_id', 't0', 'marks', 'stage_s', 'e2e_s', 'counts')

    def __init__(self, trace_id=None):
        self.trace_id = trace_id or new_trace_id()
        self.t0 = time.time()
        self.marks = {}
        self.stage_s = {}
        self.e2e_s = None
        self.counts = {}

    def add_stage(self, stage, seconds):
        """Accumulate seconds measured outside the mark chain (e.g.
        'arbitration' by the registry, the prepare half of 'pad')."""
        self.stage_s[stage] = self.stage_s.get(stage, 0.0) + float(seconds)

    def add_count(self, name, n):
        """Accumulate a per-request integer (e.g. ``decode_steps`` —
        how many decode-scan steps this generation request consumed);
        rides ``breakdown()`` next to the stage times."""
        self.counts[name] = self.counts.get(name, 0) + int(n)

    def mark(self, name, t=None):
        self.marks[name] = time.time() if t is None else t

    def finalize(self, end=None):
        """Close the trace: derive the boundary-mark stages and the
        end-to-end wall clock.  Robust to missing marks (an errored
        request finalizes with whatever boundaries it reached).
        A GENERATION request (an 'admit' mark present — ISSUE 7)
        derives prefill/decode/detokenize instead of the per-lot
        pad/dispatch/device/trim splits: its prompt pass IS one stage,
        and everything after admission belongs to the decode scan."""
        end = time.time() if end is None else end
        m = self.marks

        def seg(a, b):
            return max(m[b] - m[a], 0.0) if a in m and b in m else 0.0

        self.add_stage('queue', seg('enqueue', 'collect'))
        if 'admit' in m:
            self.add_stage('prefill', seg('collect', 'admit'))
            if 'decode_end' in m:
                self.add_stage('decode', seg('admit', 'decode_end'))
                self.add_stage('detokenize',
                               max(end - m['decode_end'], 0.0))
            else:
                # errored before any scan drained: whatever remains is
                # decode-lane time
                self.add_stage('decode', max(end - m['admit'], 0.0))
        else:
            self.add_stage('pad', seg('collect', 'lot'))
            self.add_stage('dispatch', seg('lot', 'dispatch'))
            self.add_stage('device', seg('dispatch', 'sync'))
            if 'sync' in m:
                self.add_stage('trim', max(end - m['sync'], 0.0))
        self.e2e_s = end - self.t0
        return self.stage_s

    def breakdown(self):
        """The response-surface view: trace id, end-to-end ms, and the
        per-stage ms in canonical order (only stages that occurred),
        plus any per-request counts (generation requests carry
        ``decode_steps``)."""
        out = {
            'trace_id': self.trace_id,
            'e2e_ms': (round(self.e2e_s * 1e3, 3)
                       if self.e2e_s is not None else None),
            'stages_ms': {s: round(self.stage_s[s] * 1e3, 3)
                          for s in STAGES if s in self.stage_s},
        }
        if self.counts:
            out.update(self.counts)
        return out


# ---- ambient context (cross-layer handoff) ----------------------------

_ambient = threading.local()


@contextlib.contextmanager
def attach(ctx):
    """Make ``ctx`` the calling thread's ambient trace for the block —
    the registry router attaches before engine.submit() so the engine
    threads the SAME trace id instead of minting a new one."""
    prev = getattr(_ambient, 'ctx', None)
    _ambient.ctx = ctx
    try:
        yield ctx
    finally:
        _ambient.ctx = prev


def current():
    return getattr(_ambient, 'ctx', None)


# ---- span log (the Chrome exporter's source) --------------------------

_SPAN_CAP = 8192
_span_lock = threading.Lock()
_span_log = deque(maxlen=_SPAN_CAP)
_span_state = {'enabled': 0}


def spans_enabled():
    return _span_state['enabled'] > 0


@contextlib.contextmanager
def tracing():
    """Enable span capture for the block (nested windows stack); spans
    from a previous window are cleared on the OUTERMOST entry so each
    session exports its own record."""
    with _span_lock:
        if _span_state['enabled'] == 0:
            _span_log.clear()
        _span_state['enabled'] += 1
    try:
        yield
    finally:
        with _span_lock:
            _span_state['enabled'] -= 1


def record_span(name, start_s, dur_s, trace_id=None, lane=None):
    """One timed slice in the span log; ``lane`` defaults to the
    CURRENT thread's name — spans land in per-thread lanes, which is
    exactly how the Chrome exporter renders them."""
    if not spans_enabled():
        return
    span = {
        'name': name,
        'start_s': float(start_s),
        'dur_s': float(dur_s),
        'lane': lane or threading.current_thread().name,
    }
    if trace_id is not None:
        span['trace_id'] = trace_id
    with _span_lock:
        _span_log.append(span)


def spans():
    with _span_lock:
        return list(_span_log)


def clear_spans():
    with _span_lock:
        _span_log.clear()


def dump_spans(path):
    """Write the span log as the JSON file tools/trace_export.py
    consumes; returns the span count."""
    snapshot = spans()
    with open(path, 'w') as f:
        json.dump({'spans': snapshot}, f)
    return len(snapshot)


# ---- flight recorder --------------------------------------------------

class FlightRecorder(object):
    """Bounded ring of recent dispatch/lot records.  Layers ``record``
    one small dict per dispatch (trace ids, sig, shape, timings);
    ``dump`` snapshots the ring on a worker error or a watchdog-tripped
    stall — the records ARE what was in flight.  ``last_dump`` keeps
    the most recent dump in memory (tests and post-mortems read it);
    ``dump_path`` (or the PADDLE_TPU_FLIGHT_DUMP env var) additionally
    writes each dump as JSON."""

    def __init__(self, capacity=256):
        self._lock = threading.Lock()
        self._records = deque(maxlen=int(capacity))
        self.last_dump = None
        self.dump_count = 0
        self.dump_path = None

    def record(self, kind, **fields):
        rec = dict(fields)
        rec['kind'] = kind
        rec['ts'] = time.time()
        with self._lock:
            self._records.append(rec)
        return rec

    def records(self):
        with self._lock:
            return list(self._records)

    def clear(self):
        with self._lock:
            self._records.clear()

    def dump(self, reason, **extra):
        dump = {
            'reason': reason,
            'ts': time.time(),
            'extra': extra,
            'records': self.records(),
        }
        with self._lock:
            self.last_dump = dump
            self.dump_count += 1
        path = self.dump_path or os.environ.get('PADDLE_TPU_FLIGHT_DUMP')
        if path:
            try:
                with open(path, 'w') as f:
                    json.dump(dump, f, default=repr)
            except OSError:
                pass  # a read-only fs must not mask the original error
        logging.getLogger('paddle_tpu').error(
            'flight recorder dump (%s): %d in-flight records',
            reason, len(dump['records']))
        return dump


flight_recorder = FlightRecorder()


# ---- watchdog ---------------------------------------------------------

class Watchdog(object):
    """Threshold probes over subsystem ages (oldest queued request,
    current feed stall).  A probe whose age crosses its threshold trips
    ONCE per stall episode (re-arming when the age drops back), dumping
    the flight recorder with the probe's name as the reason.  The
    polling thread starts with the first registration and exits with
    the last unregistration; ``check()`` runs one sweep synchronously
    (deterministic for tests)."""

    def __init__(self, interval_s=1.0):
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._probes = {}  # name -> [age_fn, threshold_s, tripped]
        self._thread = None
        self._stop = threading.Event()

    def register(self, name, age_fn, threshold_s, context_fn=None):
        """Returns the KEY the probe landed under — a name already held
        by a live probe is uniquified (``name#2``, ...) instead of
        silently clobbered (two same-named engines must BOTH keep their
        stall monitoring; the profiler's metrics sources learned this
        the hard way).  Callers unregister by the returned key.

        ``context_fn`` (optional, zero-arg) is called when the probe
        trips and its result lands in the dump — the subsystem's own
        "what was in flight" view (e.g. the serving engine's queued +
        undrained trace ids), which the generic ring may not hold for
        work that stalled BEFORE dispatching."""
        with self._lock:
            key, n = name, 1
            while key in self._probes:
                n += 1
                key = '%s#%d' % (name, n)
            self._probes[key] = [age_fn, float(threshold_s), False,
                                 context_fn]
            if self._thread is None:
                self._stop = threading.Event()
                self._thread = threading.Thread(
                    target=self._loop, name='trace-watchdog', daemon=True)
                self._thread.start()
        return key

    def unregister(self, name, age_fn=None):
        """Drop a probe by its registered key.  Pass ``age_fn`` to make
        the removal owner-checked: a stale GC finalizer whose key has
        since been re-registered by a NEW subsystem must not kill the
        survivor's monitoring."""
        with self._lock:
            if age_fn is not None and name in self._probes and \
                    self._probes[name][0] is not age_fn:
                return
            self._probes.pop(name, None)
            if not self._probes and self._thread is not None:
                self._stop.set()
                self._thread = None

    def check(self):
        """One sweep; returns the names that tripped this sweep."""
        with self._lock:
            probes = list(self._probes.items())
        tripped = []
        for name, state in probes:
            age_fn, threshold, was_tripped, context_fn = state
            try:
                age = age_fn()
            except Exception:
                continue  # a dying subsystem must not kill the watchdog
            if age is None:
                # nothing aging IS recovery (a drained queue, an idle
                # dispatch loop): re-arm, or a second stall episode
                # whose first observed age already exceeds the
                # threshold would never dump
                state[2] = False
                continue
            if age >= threshold and not was_tripped:
                state[2] = True
                tripped.append(name)
                extra = {}
                if context_fn is not None:
                    try:
                        extra = dict(context_fn() or {})
                    except Exception:
                        pass  # the stalled subsystem may be half-dead
                flight_recorder.dump('stall:%s' % name,
                                     age_s=round(float(age), 3),
                                     threshold_s=threshold, **extra)
            elif age < threshold:
                state[2] = False
        return tripped

    def _loop(self):
        stop = self._stop
        while not stop.wait(self.interval_s):
            self.check()


watchdog = Watchdog()


# ---- per-executable cost accounting -----------------------------------

def _abstract(x):
    """A ShapeDtypeStruct twin of an array leaf; non-array leaves
    (static ints like the scan's step count) pass through untouched so
    jit's static_argnums still see their concrete values."""
    import jax
    shape = getattr(x, 'shape', None)
    dtype = getattr(x, 'dtype', None)
    if shape is None or dtype is None or callable(shape):
        return x
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def analyze_cost(jitted, args, kind='run', steps=1, fetch_names=None):
    """AOT-lower ``jitted`` with abstract twins of ``args`` and extract
    the compiled executable's XLA cost/memory analyses.  Returns the
    cost-registry entry dict, or None when the backend exposes no
    analysis (the caller caches the outcome either way — analysis runs
    at most once per executable).

    The abstract twins never touch the real buffers, so capture is safe
    to run BEFORE a dispatch whose arguments will be donated."""
    import jax
    try:
        a_args = jax.tree_util.tree_map(_abstract, args)
        compiled = jitted.lower(*a_args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ma = compiled.memory_analysis()
    except Exception:
        return None
    steps = max(int(steps), 1)
    flops = float((ca or {}).get('flops', 0.0))
    entry = {
        'kind': kind,
        'steps': steps,
        'fetch_names': list(fetch_names or []),
        'flops': flops,
        'flops_per_step': flops / steps,
        'bytes_accessed': float((ca or {}).get('bytes accessed', 0.0)),
    }
    if ma is not None:
        entry.update({
            'argument_bytes': int(getattr(ma, 'argument_size_in_bytes', 0)),
            'output_bytes': int(getattr(ma, 'output_size_in_bytes', 0)),
            'temp_bytes': int(getattr(ma, 'temp_size_in_bytes', 0)),
            'generated_code_bytes': int(
                getattr(ma, 'generated_code_size_in_bytes', 0)),
        })
    return entry
