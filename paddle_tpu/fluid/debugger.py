"""Program debugging helpers (reference: python/paddle/fluid/debugger.py).

``pprint_program_codes`` renders programs as pseudo-code;
``draw_block_graphviz`` emits a .dot file of the op/var graph.
"""

__all__ = ['pprint_program_codes', 'pprint_block_codes',
           'draw_block_graphviz']


def pprint_program_codes(program):
    return '\n'.join(
        pprint_block_codes(blk) for blk in program.blocks)


def pprint_block_codes(block):
    lines = ['# block %d (parent %d)' % (block.idx, block.parent_idx)]
    for v in block.vars.values():
        tags = []
        if v.persistable:
            tags.append('persistable')
        if getattr(v, 'trainable', False):
            tags.append('trainable')
        lines.append('var %s : shape=%s dtype=%s %s' %
                     (v.name, list(v.shape), v.dtype, ','.join(tags)))
    for op in block.ops:
        outs = ', '.join('%s=%s' % (k, v) for k, v in op.outputs.items())
        ins = ', '.join('%s=%s' % (k, v) for k, v in op.inputs.items())
        lines.append('%s = %s(%s)' % (outs, op.type, ins))
    return '\n'.join(lines)


def draw_block_graphviz(block, highlights=None, path='./temp.dot'):
    with open(path, 'w') as f:
        f.write('digraph G {\n')
        f.write('  rankdir=TB;\n')
        for i, op in enumerate(block.ops):
            f.write('  op_%d [label="%s", shape=box, style=filled, '
                    'fillcolor="#a0cbe2"];\n' % (i, op.type))
            for n in op.input_arg_names:
                f.write('  "%s" -> op_%d;\n' % (n, i))
            for n in op.output_arg_names:
                f.write('  op_%d -> "%s";\n' % (i, n))
        f.write('}\n')
    return path
