"""Automatic mixed precision for TPU training.

bf16 inputs to matmuls/convolutions (the MXU's native multiply format) with
fp32 accumulation and fp32 master weights — everything else (batch norm
statistics, softmax, optimizer state) stays fp32.  The reference-era
analog is the float16 inference transpiler
(paddle/contrib/float16/float16_transpiler.py); on TPU this is a
trace-time mode rather than a program rewrite because XLA inserts the
casts into the fused kernels.

    with fluid.amp_guard():
        exe.run(train_program, ...)

or globally: fluid.enable_amp(True).
"""

import contextlib

from ..ops import registry as _registry

__all__ = ['amp_guard', 'enable_amp', 'amp_enabled']


def enable_amp(enabled=True):
    _registry.set_amp(enabled)


def amp_enabled():
    return _registry.amp_enabled()


@contextlib.contextmanager
def amp_guard(enable=True):
    prev = _registry.amp_enabled()
    _registry.set_amp(enable)
    try:
        yield
    finally:
        _registry.set_amp(prev)
