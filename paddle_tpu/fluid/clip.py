"""Gradient / error clipping (reference: python/paddle/fluid/clip.py)."""

import copy

from . import framework
from . import layers
from .layers import ops as _ops

__all__ = [
    'ErrorClipByValue', 'GradientClipByValue', 'GradientClipByNorm',
    'GradientClipByGlobalNorm', 'append_gradient_clip_ops',
    'error_clip_callback', 'set_gradient_clip',
]


class BaseErrorClipAttr(object):
    def _append_clip_op(self, block, grad_name):
        raise NotImplementedError()


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _append_clip_op(self, block, grad_name):
        block.append_op(
            type='clip',
            inputs={'X': [grad_name]},
            outputs={'Out': [grad_name]},
            attrs={'min': self.min,
                   'max': self.max})


def error_clip_callback(block, context):
    op = context['op']
    for grad_n in [n for ns in op.outputs.values() for n in ns if n]:
        base = grad_n.split('@RENAME@')[0]
        if not base.endswith(framework.GRAD_VAR_SUFFIX):
            continue
        fwd_var = block._find_var_recursive(
            base[:-len(framework.GRAD_VAR_SUFFIX)])
        if fwd_var is None:
            continue
        error_clip = getattr(fwd_var, 'error_clip', None)
        if error_clip is not None:
            error_clip._append_clip_op(block, grad_n)


class BaseGradientClipAttr(object):
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        raise NotImplementedError()


class NullGradientClipAttr(BaseGradientClipAttr):
    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _create_operators(self, param, grad):
        new_grad = layers.clip(x=grad, min=self.min, max=self.max)
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _create_operators(self, param, grad):
        new_grad = layers.clip_by_norm(x=grad, max_norm=self.clip_norm)
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Scale all grads by clip_norm/max(global_norm, clip_norm)
    (reference clip.py GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm, group_name='default_group'):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + '_clip_value'] = self.clip_norm
            context[self.group_name + '_clip'] = layers.fill_constant(
                shape=[1], dtype='float32', value=self.clip_norm)
        local_norm_var = layers.reduce_sum(
            input=_ops.square(grad))
        context[self.group_name].append(local_norm_var)
        self.context = context

    def _create_operators(self, param, grad):
        group_scale_name = self.group_name + '_scale'
        if group_scale_name not in self.context:
            group_norm_var = layers.sums(input=self.context[self.group_name])
            group_norm_var = _ops.sqrt(x=group_norm_var)
            clip_var = self.context[self.group_name + '_clip']
            group_scale_var = layers.elementwise_div(
                x=clip_var,
                y=layers.elementwise_max(x=clip_var, y=group_norm_var))
            self.context[group_scale_name] = group_scale_var
        new_grad = layers.elementwise_mul(
            x=grad, y=self.context[group_scale_name])
        return param, new_grad


_gradient_clip_attr = None


def set_gradient_clip(clip, param_list=None, program=None):
    global _gradient_clip_attr
    if program is None:
        program = framework.default_main_program()
    if param_list is None:
        param_list = program.all_parameters()
    param_list = [
        program.global_block().var(p) if isinstance(p, str) else p
        for p in param_list
    ]
    for param in param_list:
        param.gradient_clip_attr = copy.deepcopy(clip)
    _gradient_clip_attr = clip


def append_gradient_clip_ops(param_grad):
    context = dict()
    res = []
    for p, g in param_grad:
        clip_attr = getattr(p, 'gradient_clip_attr', None) or \
            NullGradientClipAttr()
        clip_attr._process_context(context=context, param=p, grad=g)
    for p, g in param_grad:
        clip_attr = getattr(p, 'gradient_clip_attr', None) or \
            NullGradientClipAttr()
        res.append(clip_attr._create_operators(param=p, grad=g))
    return res
