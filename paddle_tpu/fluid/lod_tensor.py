"""LoDTensor helpers (reference: python/paddle/fluid/lod_tensor.py)."""

import numpy as np

from . import core

__all__ = ['create_lod_tensor', 'create_random_int_lodtensor']


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Create a LoDTensor from numpy / list data + per-level lengths
    (reference lod_tensor.py:24)."""
    if isinstance(data, core.LoDTensor):
        return create_lod_tensor(data.numpy(), recursive_seq_lens, place)
    if isinstance(data, list):
        # flatten through all LoD nesting levels down to per-sequence rows
        # (reference lod_tensor.py:24 accepts arbitrarily nested lists)
        rows = data
        for _ in range(len(recursive_seq_lens) - 1):
            rows = [seq for group in rows for seq in group]
        arrs = [np.asarray(row).reshape(len(row), -1) if not np.isscalar(
            row) else np.asarray([[row]]) for row in rows]
        data = np.concatenate(arrs, axis=0)
    data = np.asarray(data)
    t = core.LoDTensor(data)
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    assert t.has_valid_recursive_sequence_lengths(), \
        'invalid recursive_seq_lens for data shape %s' % (data.shape, )
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place, low,
                                high):
    assert isinstance(base_shape, list), 'base_shape should be a list'
    converted_lod = recursive_seq_lens[-1]
    total = sum(converted_lod)
    shape = [total] + base_shape
    data = np.random.random_integers(low, high, shape).astype('int64')
    t = core.LoDTensor(data)
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    return t
