"""ParallelExecutor: data-parallel (and tensor-parallel) SPMD execution.

Reference design (framework/parallel_executor.cc:119, details/*): clone the
program per GPU, build an SSA graph, insert NCCL AllReduce op-handles at
each param grad, run with a threadpool.  TPU-native design: the SAME traced
block as the single-device Executor, jitted once with GSPMD shardings —
feeds sharded batch-dim over the 'dp' mesh axis, params replicated (or
sharded per their annotations, paddle_tpu.parallel.shard), gradient
averaging emerges as compiler-inserted cross-replica sums on ICI.

BuildStrategy/ExecutionStrategy are accepted for API parity
(details/build_strategy.h:23, execution_strategy.h:21); reduce-scatter
('kReduce') maps to GSPMD's own choice of collectives.
"""

import numpy as np

from . import core
from .executor import _CompiledBlock, _to_device_value, _current_scope, \
    as_numpy, prepare_feed_arrays, feed_signature, _is_host_op
from .framework import default_main_program, Variable
from ..ops import registry

__all__ = ['ParallelExecutor', 'ExecutionStrategy', 'BuildStrategy']


class ExecutionStrategy(object):
    def __init__(self):
        self.num_threads = 0
        self.use_event = True
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100


class BuildStrategy(object):
    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ''


class _SpmdCompiledBlock(_CompiledBlock):
    """A _CompiledBlock whose jit carries GSPMD shardings over a mesh."""

    def __init__(self, program, block_idx, feed_names, fetch_names, mesh,
                 scope, batch_axis='dp'):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        # build the plain traced fn + state analysis first
        place = core.TPUPlace()
        super(_SpmdCompiledBlock, self).__init__(
            program, block_idx, feed_names, fetch_names, place, scope)
        self.mesh = mesh
        # expose to mesh-aware lowerings (ring attention) at trace time
        self._spmd_ref['mesh'] = mesh
        self._spmd_ref['batch_axis'] = batch_axis
        self.batch_axis = batch_axis
        from ..parallel.api import sharding_of

        def var_sharding(name):
            v = self.block._find_var_recursive(name)
            spec = sharding_of(v) if v is not None else None
            return NamedSharding(mesh, spec if spec is not None else P())

        rw_shardings = {n: var_sharding(n) for n in self.state_rw}
        ro_shardings = {n: var_sharding(n) for n in self.state_ro}
        feed_shardings = {}
        for n in self.feed_names:
            v = self.block._find_var_recursive(n)
            spec = sharding_of(v)
            if spec is None:
                # shard batch dim over data parallel when the mesh has it
                spec = P(batch_axis) if batch_axis in mesh.axis_names \
                    else P()
            feed_shardings[n] = NamedSharding(mesh, spec)
        out_state_shardings = {
            n: var_sharding(n)
            for n in self.state_out
        }
        self._feed_shardings = feed_shardings
        self._state_shardings = dict(rw_shardings, **ro_shardings)
        donate = (0, ) if self.state_rw else ()
        self._jit = jax.jit(
            self._fn,
            in_shardings=(rw_shardings, ro_shardings, feed_shardings, None),
            out_shardings=(out_state_shardings, None),
            donate_argnums=donate)

    def run(self, scope, feed_values, rng_key, eager=False):
        import jax

        def to_value(val, desc):
            if isinstance(val, core.LoDTensor):
                val = val.numpy()
            return val  # device_put with shardings happens via jit

        state_rw = self._state_from_scope(scope, self.state_rw, to_value)
        state_ro = self._state_from_scope(scope, self.state_ro, to_value)
        for name in list(state_rw) + list(state_ro):
            tgt = state_rw if name in state_rw else state_ro
            tgt[name] = jax.device_put(tgt[name],
                                       self._state_shardings[name])
        feeds = {}
        for n, v in feed_values.items():
            if isinstance(v, core.LoDTensor):
                v = v.numpy()
            if not isinstance(v, jax.Array):
                v = np.asarray(v)
            # device arrays (double-buffer prefetch) reshard device-side
            feeds[n] = jax.device_put(v, self._feed_shardings[n])
        new_state, fetches = self._jit(state_rw, state_ro, feeds, rng_key)
        for name, val in new_state.items():
            scope.var(name).set_value(val)
        return fetches


class ParallelExecutor(object):
    """API parity with reference parallel_executor.py:36."""

    def __init__(self,
                 use_cuda=False,
                 loss_name=None,
                 main_program=None,
                 share_vars_from=None,
                 exec_strategy=None,
                 build_strategy=None,
                 num_trainers=1,
                 trainer_id=0,
                 scope=None,
                 mesh=None,
                 **kwargs):
        from ..parallel import make_mesh
        self._main_program = main_program if main_program is not None \
            else default_main_program()
        self._scope = scope if scope is not None else _current_scope()
        self._mesh = mesh if mesh is not None else make_mesh()
        self._loss_name = loss_name
        self._cache = {}
        self._rng = None
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        self.build_strategy = build_strategy or BuildStrategy()

    @property
    def device_count(self):
        return int(np.prod(self._mesh.devices.shape))

    def _next_rng(self):
        import jax
        if self._rng is None:
            self._rng = jax.random.PRNGKey(
                self._main_program.random_seed or 0)
        self._rng, key = jax.random.split(self._rng)
        return key

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        program = self._main_program
        scope = self._scope
        feed = feed if feed is not None else (feed_dict or {})
        if isinstance(fetch_list, (Variable, str)):
            fetch_list = [fetch_list]
        fetch_names = [
            f.name if isinstance(f, Variable) else str(f) for f in fetch_list
        ]
        from .executor import _pop_readers_into_feed
        feed = dict(feed)
        _pop_readers_into_feed(program, feed)
        feed_arrays = prepare_feed_arrays(feed)
        sig = feed_signature(feed_arrays)
        key = (id(program), program._version, tuple(fetch_names), sig,
               registry.amp_enabled())
        compiled = self._cache.get(key)
        if compiled is None:
            host = [op.type for op in program.global_block().ops
                    if _is_host_op(op)]
            if host:
                raise NotImplementedError(
                    'ParallelExecutor cannot run programs containing host '
                    'ops %s — run them with fluid.Executor' % sorted(set(host)))
            compiled = _SpmdCompiledBlock(program, 0, [n for n, _, _ in sig],
                                          fetch_names, self._mesh, scope)
            self._cache[key] = compiled
        fetches = compiled.run(scope, feed_arrays, self._next_rng())
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [core.LoDTensor(np.asarray(f)) for f in fetches]

    def bcast_params(self):
        """Reference BCastParamsToDevices (parallel_executor.cc:169) — a
        no-op under GSPMD: replication is a sharding, not a copy loop."""
        pass
