"""ParallelExecutor: data-parallel (and tensor-parallel) SPMD execution.

Reference design (framework/parallel_executor.cc:119, details/*): clone the
program per GPU, build an SSA graph, insert NCCL AllReduce op-handles at
each param grad, run with a threadpool.  TPU-native design: the SAME traced
block as the single-device Executor, jitted once with GSPMD shardings —
feeds sharded batch-dim over the 'dp' mesh axis, params replicated (or
sharded per their annotations, paddle_tpu.parallel.shard), gradient
averaging emerges as compiler-inserted cross-replica sums on ICI.

BuildStrategy/ExecutionStrategy are accepted for API parity
(details/build_strategy.h:23, execution_strategy.h:21); reduce-scatter
('kReduce') maps to GSPMD's own choice of collectives.
"""

import threading

import numpy as np

from . import core
from .executor import _CompiledBlock, _current_scope, \
    prepare_feed_arrays, feed_signature, _is_host_op, \
    _reject_reader_fed, check_feed_list_uniform, stack_steps, \
    check_feed_list_names, normalize_trailing_feed_list
from .framework import default_main_program, Variable
from ..ops import registry

__all__ = ['ParallelExecutor', 'ExecutionStrategy', 'BuildStrategy']


def _lead(v):
    """Leading dim of a feed value (LoDTensor exposes shape() as a
    method, so np.shape would return the bound method); None for
    scalars."""
    shape = v.shape() if isinstance(v, core.LoDTensor) else np.shape(v)
    return int(shape[0]) if len(shape) >= 1 else None


def pad_ragged_batch(feed_arrays, multiple, target=None, force_mask=False,
                     skip=(), batch_names=None, sizes_only=False,
                     report=None):
    """DataBalance parity (details/data_balance_op_handle.cc) under static
    SPMD shapes: pad the lot's batch dim up to ``target`` (default: the
    next multiple of the mesh's dp extent) by replicating the last real
    sample, and inject a ``registry.SAMPLE_MASK_NAME`` feed (1.0 = real
    row, 0.0 = padding) so batch-mean lowerings — and, through jax.vjp,
    every gradient flowing out of them — weight by the REAL sample count.
    An epoch whose final lot isn't divisible by bs*ndev then trains with
    the numerics of the unpadded lot instead of dying on a raw JAX
    sharding error.

    The batch row count is the NON-DIVISIBLE leading dim among the
    dp-sharded feeds (names in ``skip`` — feeds with explicit sharding
    annotations — never vote): a divisible non-batch feed (a lookup
    table, a replicated aux input) cannot hijack the inference, and two
    feeds disagreeing on non-divisible rows is an error, not a guess.
    ``batch_names`` skips inference entirely — only those feeds are
    batch-led (run_multi's re-pad pass, where a lot that already
    divides carries no inference signal of its own).

    Returns (feed_arrays, n_real, n_padded); the input dict is returned
    untouched when the lot already divides (and no mask is forced).
    ``sizes_only`` runs just the inference — (None, n_real, n_padded) —
    so a probing pass over a feed_list never copies device-staged
    arrays through the host.  ``report`` (a dict) receives
    ``batch_names``: the feed names treated as batch-led, recorded
    PRE-padding — post-padding every batch feed shares the padded row
    count with any coinciding aux feed, so this is the only place the
    distinction still exists."""
    dims = set()
    for n, v in feed_arrays.items():
        if n in skip or isinstance(v, core.SelectedRows):
            continue
        if batch_names is not None and n not in batch_names:
            continue
        d = _lead(v)
        if d is not None:
            dims.add(d)
    dims = sorted(dims)
    if batch_names is not None:
        if len(dims) != 1:
            raise ValueError(
                'ragged lot is ambiguous: batch feeds %s disagree on '
                'rows %s' % (sorted(batch_names), dims))
        b = dims[0]
        if target is not None:
            tgt = int(target)
        else:
            tgt = -(-b // multiple) * multiple if multiple > 1 else b
    elif target is not None:
        # a lot that already divides carries no inference signal of its
        # own — the caller must say which feeds are batch-led
        raise ValueError(
            'pad_ragged_batch: target= requires batch_names=')
    elif multiple > 1:
        nondiv = [d for d in dims if d % multiple]
        if len(nondiv) > 1:
            raise ValueError(
                'ragged lot is ambiguous: feeds disagree on batch rows '
                '%s (each %% %d != 0) — pad them to one batch size '
                'first, or annotate non-batch feeds with '
                'paddle_tpu.parallel.shard' % (nondiv, multiple))
        b = nondiv[0] if nondiv else (dims[-1] if dims else 0)
        tgt = -(-b // multiple) * multiple if nondiv else b
    else:
        b = dims[-1] if dims else 0
        tgt = b
    if report is not None:
        report['batch_names'] = {
            n for n, v in feed_arrays.items()
            if n not in skip and not isinstance(v, core.SelectedRows)
            and (batch_names is None or n in batch_names)
            and _lead(v) == b}
    if b == 0 or (tgt == b and not force_mask):
        return (None if sizes_only else feed_arrays), b, b
    if sizes_only:
        return None, b, tgt
    out = {}
    pad = tgt - b
    for n, v in feed_arrays.items():
        if isinstance(v, core.LoDTensor):
            v = v.numpy()  # lod-free pass-through tensors (lod ones were
            # already lowered to padded + @SEQLEN by prepare_feed_arrays)
        if n in skip or isinstance(v, core.SelectedRows) \
                or (batch_names is not None and n not in batch_names) \
                or np.ndim(v) < 1 or np.shape(v)[0] != b \
                or not pad:
            out[n] = v  # not batch-leading, or nothing to append —
            # leave device-staged arrays on device
            continue
        a = np.asarray(v)
        # replicate the last REAL sample: always a valid row (in-range
        # indices, finite activations); its loss/grads are masked out
        out[n] = np.concatenate(
            [a, np.broadcast_to(a[-1:], (pad, ) + a.shape[1:])])
    mask = np.zeros((tgt, ), np.float32)
    mask[:b] = 1.0
    out[registry.SAMPLE_MASK_NAME] = mask
    return out, b, tgt


def normalize_ragged_feed_list(per_step, pad_fn):
    """Shared ragged-feed_list normalization behind run_multi and
    run_eval_multi (single-device and SPMD): size-probe every lot, and
    when any is ragged (or lots disagree in rows) re-pad ALL of them to
    the common target with masked samples so the scan's per-step
    structure stays uniform.  The batch feeds are the ones whose rows
    VARY across lots; all-identical lots fall back to the first pass's
    inference — a divisible aux feed can't vote either way.

    pad_fn(feed_arrays, **kw) -> (feed_arrays, n_real, n_padded) — the
    executor's padding policy (multiple=1 for single-device,
    ParallelExecutor._pad_ragged for the dp-extent rule).

    Returns (per_step, reals, target, batch_feed_names); ``reals`` is
    the per-lot real row count, or None when nothing was padded."""
    probed = [pad_fn(fa, sizes_only=True) for fa in per_step]
    target = max(p[2] for p in probed)
    if not any(p[2] != target or p[1] != target for p in probed):
        return per_step, None, target, None
    batch_names = {
        n for n in per_step[0]
        if len({_lead(fa[n]) for fa in per_step}) > 1
    } or {n for n, v in per_step[0].items()
          if _lead(v) == probed[0][1]}
    rpt = {}
    repadded = [pad_fn(fa, target=target, force_mask=True,
                       batch_names=batch_names, report=rpt)
                for fa in per_step]
    return ([p[0] for p in repadded], [p[1] for p in repadded], target,
            rpt.get('batch_names'))


class ExecutionStrategy(object):
    def __init__(self):
        self.num_threads = 0
        self.use_event = True
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100


class BuildStrategy(object):
    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ''


class _SpmdCompiledBlock(_CompiledBlock):
    """A _CompiledBlock whose jit carries GSPMD shardings over a mesh."""

    def __init__(self, program, block_idx, feed_names, fetch_names, mesh,
                 scope, batch_axis='dp'):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        # build the plain traced fn + state analysis first
        place = core.TPUPlace()
        super(_SpmdCompiledBlock, self).__init__(
            program, block_idx, feed_names, fetch_names, place, scope)
        self.mesh = mesh
        # expose to mesh-aware lowerings (ring attention) at trace time
        self._spmd_ref['mesh'] = mesh
        self._spmd_ref['batch_axis'] = batch_axis
        self.batch_axis = batch_axis
        from ..parallel.api import sharding_of

        def var_sharding(name):
            v = self.block._find_var_recursive(name)
            spec = sharding_of(v) if v is not None else None
            return NamedSharding(mesh, spec if spec is not None else P())

        rw_shardings = {n: var_sharding(n) for n in self.state_rw}
        ro_shardings = {n: var_sharding(n) for n in self.state_ro}
        feed_shardings = {}
        for n in self.feed_names:
            v = self.block._find_var_recursive(n)
            spec = sharding_of(v)
            if spec is None:
                # shard batch dim over data parallel when the mesh has it
                spec = P(batch_axis) if batch_axis in mesh.axis_names \
                    else P()
            feed_shardings[n] = NamedSharding(mesh, spec)
        out_state_shardings = {
            n: var_sharding(n)
            for n in self.state_out
        }
        self._feed_shardings = feed_shardings
        self._state_shardings = dict(rw_shardings, **ro_shardings)
        self._out_state_shardings = out_state_shardings
        donate = (0, ) if self.state_rw else ()
        self._jit = jax.jit(
            self._fn,
            in_shardings=(rw_shardings, ro_shardings, feed_shardings, None),
            out_shardings=(out_state_shardings, None),
            donate_argnums=donate)

    def _materialize_args(self, scope, feed_values, cache_ro=False):
        """Sharded device staging: state and feeds go to the mesh via
        their GSPMD shardings (device arrays from a double-buffer
        prefetch reshard device-side).  The base class's run()/
        run_multi() call this polymorphically, so both the single-step
        and the K-steps-per-dispatch paths are shared with Executor.
        ``cache_ro`` mirrors the base class's host-state caching (the
        r5 lesson, now for dp serving): READ-ONLY state staged from a
        host array is written back to the scope as its SHARDED device
        array, so every later dispatch reshards in place instead of
        re-uploading all params through the tunnel — and the engine's
        ``device_footprint()`` sees the buffers the mesh really pins.
        RW state is never cached (its staged buffer is donated)."""
        import jax

        def to_value(val, desc):
            if isinstance(val, core.LoDTensor):
                val = val.numpy()
            return val  # sharded device_put happens below

        state_rw = self._state_from_scope(scope, self.state_rw, to_value)
        state_ro = self._state_from_scope(scope, self.state_ro, to_value)
        for name in list(state_rw) + list(state_ro):
            tgt = state_rw if name in state_rw else state_ro
            staged = jax.device_put(tgt[name],
                                    self._state_shardings[name])
            tgt[name] = staged
            if cache_ro and name in state_ro:
                var = scope.find_var(name)
                raw = var.value()
                if not isinstance(raw, jax.Array):
                    lod = raw.lod() if isinstance(raw, core.LoDTensor) \
                        else None
                    if not lod:
                        var.set_value(staged)
        feeds = {}
        for n, v in feed_values.items():
            if isinstance(v, core.LoDTensor):
                v = v.numpy()
            if not isinstance(v, jax.Array):
                v = np.asarray(v)
            feeds[n] = jax.device_put(v, self._feed_shardings[n])
        return state_rw, state_ro, feeds

    def scanned_sharding(self, name):
        """Sharding for a scanned feed: the per-step spec shifted right
        of the leading K (steps) axis, which is never sharded."""
        from jax.sharding import NamedSharding
        from ..parallel.api import scanned_spec
        return NamedSharding(
            self.mesh, scanned_spec(self._feed_shardings[name].spec))

    def _wrap_multi_jit(self, feeds, scanned, donate):
        """The shared K-steps-per-dispatch scan, jitted with this
        block's GSPMD shardings and the base class's donation plan
        (RW state + the scanned feed block on device).  The base
        class's per-(feeds, scanned)-structure cache keys it — the
        ragged-tail masked lot and the full lot key different
        structures, each compiled once."""
        import jax
        rw_sh = {n: self._state_shardings[n] for n in self.state_rw}
        ro_sh = {n: self._state_shardings[n] for n in self.state_ro}
        feed_sh = {n: self._feed_shardings[n] for n in feeds}
        scanned_sh = {n: self.scanned_sharding(n) for n in scanned}
        return jax.jit(
            self._make_multi(), static_argnums=(5, ),
            in_shardings=(rw_sh, ro_sh, feed_sh, scanned_sh, None),
            out_shardings=(self._out_state_shardings, None),
            donate_argnums=donate)

    def _device_platform(self):
        return self.mesh.devices.flat[0].platform

    def _wrap_decode_multi_jit(self, feeds, carry, spec, donate):
        """The shared K-decode-steps-per-dispatch scan (ISSUE 7),
        jitted with this block's GSPMD shardings: every slot-carry leaf
        (KV/hidden state, token, alive mask, step budget) shards its
        SLOT dim over the batch axis — the decode cache lives
        distributed across the mesh and updates in place there — and
        the emitted [K, S] token/alive stacks shard the slot dim right
        of the unsharded step axis, like every scanned output."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.api import scanned_spec
        mesh = self.mesh
        row_spec = P(self.batch_axis) \
            if self.batch_axis in mesh.axis_names else P()
        row = NamedSharding(mesh, row_spec)
        ro_sh = {n: self._state_shardings[n] for n in self.state_ro}
        feed_sh = {n: self._feed_shardings[n] for n in feeds}
        carry_sh = {
            'state': {n: self._state_shardings[n]
                      for n in self.state_rw},
            'slots': {n: self._feed_shardings[n]
                      for n in carry['slots']},
            'token': self._feed_shardings[spec['token']],
            'alive': row, 'remaining': row,
        }
        out_row = NamedSharding(mesh, scanned_spec(row_spec))
        return jax.jit(
            self._make_decode_multi(spec), static_argnums=(4, ),
            in_shardings=(ro_sh, feed_sh, carry_sh, None),
            out_shardings=(carry_sh, out_row, out_row),
            donate_argnums=donate)

    def _wrap_chunk_prefill_jit(self, feeds, carry, spec, donate):
        """The chunk-prefill advance (ISSUE 14), jitted with this
        block's GSPMD shardings: the slot carry shards like the decode
        scan's, the [S, C, 1] token block (and its @SEQLEN/length
        companions) shards its slot dim over the batch axis, and the
        aux active/finish/budget leaves ride the same row sharding."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self.mesh
        row_spec = P(self.batch_axis) \
            if self.batch_axis in mesh.axis_names else P()
        row = NamedSharding(mesh, row_spec)
        ro_sh = {n: self._state_shardings[n] for n in self.state_ro}
        feed_sh = {n: self._feed_shardings.get(n, row) for n in feeds}
        carry_sh = {
            'state': {n: self._state_shardings[n]
                      for n in self.state_rw},
            'slots': {n: self._feed_shardings[n]
                      for n in carry['slots']},
            'token': row, 'alive': row, 'remaining': row,
        }
        aux_sh = {'active': row, 'finish': row, 'budget': row}
        return jax.jit(
            self._make_chunk_prefill(spec),
            in_shardings=(ro_sh, feed_sh, carry_sh, aux_sh, None),
            out_shardings=(carry_sh, row),
            donate_argnums=donate)

    def _wrap_eval_multi_jit(self, feeds, scanned, donate):
        """The shared K-eval-batches-per-dispatch scan, jitted with this
        block's GSPMD shardings (feeds/lots sharded batch-dim over 'dp'
        for sharded serving) and the base class's donation plan."""
        import jax
        rw_sh = {n: self._state_shardings[n] for n in self.state_rw}
        ro_sh = {n: self._state_shardings[n] for n in self.state_ro}
        feed_sh = {n: self._feed_shardings[n] for n in feeds}
        scanned_sh = {n: self.scanned_sharding(n) for n in scanned}
        return jax.jit(
            self._make_eval_multi(), static_argnums=(5, ),
            in_shardings=(rw_sh, ro_sh, feed_sh, scanned_sh, None),
            out_shardings=(self._out_state_shardings, None),
            donate_argnums=donate)


class ParallelExecutor(object):
    """API parity with reference parallel_executor.py:36."""

    def __init__(self,
                 use_cuda=False,
                 loss_name=None,
                 main_program=None,
                 share_vars_from=None,
                 exec_strategy=None,
                 build_strategy=None,
                 num_trainers=1,
                 trainer_id=0,
                 scope=None,
                 mesh=None,
                 **kwargs):
        from ..parallel import make_mesh
        self._main_program = main_program if main_program is not None \
            else default_main_program()
        self._scope = scope if scope is not None else _current_scope()
        self._mesh = mesh if mesh is not None else make_mesh()
        self._loss_name = loss_name
        self._cache = {}
        # guards cache iteration/mutation between the dispatch thread
        # and metrics/bench readers (cost_report) — and the engine's
        # drop_executables purge path picks it up by name, like the
        # Executor's (PR 4 concurrent-predictor contract)
        self._cache_lock = threading.Lock()
        self._rng = None
        self.exec_strategy = exec_strategy or ExecutionStrategy()
        self.build_strategy = build_strategy or BuildStrategy()
        self._batch_axis = 'dp'
        # observability (mirrors Executor): compile_count counts XLA
        # traces (block compiles + multi-step executables); dispatch
        # accounting lets the contract tests pin K steps per dispatch
        self.compile_count = 0
        self.dispatch_count = 0
        self.steps_dispatched = 0

    @property
    def device_count(self):
        return int(np.prod(self._mesh.devices.shape))

    def _dp_extent(self):
        """Rows-per-lot divisibility requirement: the mesh's extent
        along the batch axis (1 when the mesh has no 'dp' axis —
        batch replicated, nothing to pad for)."""
        axes = dict(zip(self._mesh.axis_names, self._mesh.devices.shape))
        return int(axes.get(self._batch_axis, 1))

    def _annotated_feed_names(self, feed_arrays):
        """Feed names carrying an explicit sharding annotation (and
        their @SEQLEN/@ROWS sidebands): laid out per their spec, not
        dp-sharded on dim 0, so they must not vote in (or be padded
        by) ragged-batch inference."""
        from ..parallel.api import sharding_of
        block = self._main_program.block(0)
        skip = set()
        for n in feed_arrays:
            base = n
            for suffix in (registry.SEQLEN_SUFFIX, registry.ROWS_SUFFIX):
                if base.endswith(suffix):
                    base = base[:-len(suffix)]
            v = block.vars.get(base)
            if v is not None and sharding_of(v) is not None:
                skip.add(n)
        return skip

    def _pad_ragged(self, feed_arrays, **kw):
        return pad_ragged_batch(
            feed_arrays, self._dp_extent(),
            skip=self._annotated_feed_names(feed_arrays), **kw)

    def _next_rng(self):
        import jax
        if self._rng is None:
            self._rng = jax.random.PRNGKey(
                self._main_program.random_seed or 0)
        self._rng, key = jax.random.split(self._rng)
        return key

    def _fetch_names(self, fetch_list):
        if isinstance(fetch_list, (Variable, str)):
            fetch_list = [fetch_list]
        return [
            f.name if isinstance(f, Variable) else str(f) for f in fetch_list
        ]

    def _resolve(self, fetch_names, feed_arrays, batch_feed_names=None):
        """Find (or compile) the sharded executable for this
        (program version, fetch list, feed signature).
        batch_feed_names: which feeds the ragged padding treated as
        batch-led (recorded PRE-padding) — seeds the trace's provenance
        so an aux feed whose rows coincide with the padded batch size
        is never masked or trimmed."""
        program = self._main_program
        sig = feed_signature(feed_arrays)
        key = (id(program), program._version, tuple(fetch_names), sig,
               registry.amp_enabled())
        with self._cache_lock:
            compiled = self._cache.get(key)
        if compiled is None:
            host = [op.type for op in program.global_block().ops
                    if _is_host_op(op)]
            if host:
                raise NotImplementedError(
                    'ParallelExecutor cannot run programs containing host '
                    'ops %s — run them with fluid.Executor' % sorted(set(host)))
            self.compile_count += 1
            compiled = _SpmdCompiledBlock(program, 0, [n for n, _, _ in sig],
                                          fetch_names, self._mesh,
                                          self._scope,
                                          batch_axis=self._batch_axis)
            # the inference is deterministic in the feed signature, so
            # setting this once at compile time is consistent for every
            # later cache hit
            compiled._batch_feed_names = (
                frozenset(batch_feed_names)
                if batch_feed_names is not None else None)
            with self._cache_lock:
                self._cache[key] = compiled
        return compiled

    def _convert_fetches(self, fetches, return_numpy, real=0, padded=0,
                         compiled=None):
        if real != padded:
            # a per-sample fetch over a padded lot carries fabricated
            # rows: trim the BATCH-LED ones (per the trace's provenance,
            # recorded at compile time) back to the REAL count so eval
            # loops never score the replicated samples — a parameter
            # whose dim 0 coincides with the padded size stays whole
            from .executor import fetch_batch_led
            led = fetch_batch_led(compiled, len(fetches))
            fetches = [
                f[:real] if is_led and getattr(f, 'ndim', 0) >= 1
                and np.shape(f)[0] == padded else f
                for f, is_led in zip(fetches, led)
            ]
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [core.LoDTensor(np.asarray(f)) for f in fetches]

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        program = self._main_program
        feed = feed if feed is not None else (feed_dict or {})
        fetch_names = self._fetch_names(fetch_list)
        from .executor import _pop_readers_into_feed
        feed = dict(feed)
        _pop_readers_into_feed(program, feed)
        rpt = {}
        feed_arrays, real, padded = self._pad_ragged(
            prepare_feed_arrays(feed), report=rpt)
        compiled = self._resolve(fetch_names, feed_arrays,
                                 rpt.get('batch_names'))
        fetches = compiled.run(self._scope, feed_arrays, self._next_rng())
        # count only dispatches that actually ran
        self.dispatch_count += 1
        self.steps_dispatched += 1
        return self._convert_fetches(fetches, return_numpy, real, padded,
                                     compiled=compiled)

    def run_multi(self, fetch_list, feed=None, steps=1, feed_list=None,
                  return_numpy=True, reader=None, embed_caches=None):
        """Run ``steps`` iterations as ONE GSPMD-sharded device dispatch
        (the SPMD counterpart of Executor.run_multi; the reference
        amortizes per-iteration overhead with its double-buffered
        multi-iteration loop, executor.cc:321-339).  Returns the LAST
        iteration's fetches; state persists to the scope exactly as
        ``steps`` sequential run() calls would.

        feed: one lot reused every iteration (fori_loop), OR
        feed_list: per-iteration lots scanned on device (``steps`` is
        then len(feed_list)), OR
        reader: the program's py_reader — ``steps`` DISTINCT fresh
        minibatches drain from its queue and ride the feed_list path
        (so ragged reader lots pad to the dp extent with masked
        samples exactly like explicit ones).  Ragged lots — including
        a ragged FINAL lot in feed_list — are padded to the dp extent
        with masked samples; loss/grad means weight by the real sample
        count."""
        import jax
        if reader is not None:
            from .dataflow import check_reader_args, drain_reader_feed_list
            check_reader_args('run_multi', feed, feed_list)
            feed_list = drain_reader_feed_list(self._main_program, reader,
                                               steps)
        else:
            _reject_reader_fed(self._main_program,
                               'ParallelExecutor.run_multi')
        fetch_names = self._fetch_names(fetch_list)
        scanned = None
        exchanges = []

        def _stage_caches(per_step_or_feed, k):
            # ISSUE 12: remap each cache's id feeds to slab slots IN
            # PLACE before signatures/padding see them (the padded tail
            # replicates already-remapped rows, so every slot stays
            # valid), recording the exchange to apply pre-dispatch
            # EVERY cache's scope binding is checked before ANY cache
            # stages: a mis-bound second cache must not leave the first
            # with a staged exchange (and skewed hit-rate metrics) for
            # a block that never dispatches — same invariant as
            # Executor.run_multi's pre-staging check
            for cache in (embed_caches or ()):
                cache.check_scope(self._scope,
                                  'ParallelExecutor.run_multi')
            for cache in (embed_caches or ()):
                exchanges.append(
                    (cache,
                     cache.stage_feed_list(per_step_or_feed, steps=k)))

        if feed_list is not None:
            if feed is not None:
                raise ValueError('run_multi: pass feed OR feed_list')
            if not feed_list:
                raise ValueError('run_multi: feed_list is empty')
            per_step = [prepare_feed_arrays(dict(f)) for f in feed_list]
            steps = len(per_step)
            check_feed_list_names(per_step, 'run_multi')
            _stage_caches(per_step, steps)
            normalize_trailing_feed_list(per_step)
            # size probe only — no lot is padded (or pulled off device)
            # unless something is actually ragged
            per_step, reals, target, batch_feed_names = \
                normalize_ragged_feed_list(per_step, self._pad_ragged)
            real, n_padded = \
                (reals[-1] if reals is not None else target), target
            check_feed_list_uniform(per_step)
            compiled = self._resolve(fetch_names, per_step[0],
                                     batch_feed_names)
            scanned = {
                n: jax.device_put(stack_steps([fa[n] for fa in per_step]),
                                  compiled.scanned_sharding(n))
                for n in per_step[0]
            }
            feed_arrays = {}  # every feed name arrives via the scan
        else:
            rpt = {}
            prepared = prepare_feed_arrays(
                dict(feed if feed is not None else {}))
            _stage_caches([prepared], steps)
            feed_arrays, real, n_padded = self._pad_ragged(
                prepared, report=rpt)
            compiled = self._resolve(fetch_names, feed_arrays,
                                     rpt.get('batch_names'))
        for cache, ex in exchanges:
            # the block's row exchange lands right before its dispatch
            cache.apply(ex)
        fetches = compiled.run_multi(self._scope, feed_arrays,
                                     self._next_rng(), steps,
                                     scanned_feeds=scanned)
        # accounting AFTER the dispatch, so a failed call (steps < 1,
        # shape error inside jit) can't skew the observability
        # counters.  Each (steps, scanned shape signature) is its own
        # XLA compile of the multi-step executable (steps is static).
        if compiled.note_multi_compile(steps, scanned):
            self.compile_count += 1
        self.dispatch_count += 1
        self.steps_dispatched += int(steps)
        # fetches come from the LAST iteration: trim to its real rows
        return self._convert_fetches(fetches, return_numpy, real, n_padded,
                                     compiled=compiled)

    def _dispatch_multi_scanned(self, fetch_list, sig_feed, scanned,
                                steps, batch_feed_names=None):
        """Async front half of a scanned SPMD run_multi dispatch (the
        FeedPipeline's dp>1 path): resolve the sharded executable keyed
        on ``sig_feed``, dispatch ONE pre-staged dp-sharded scanned
        block, and return the raw device fetches with NO host sync —
        the SPMD mirror of Executor._dispatch_multi_scanned.
        batch_feed_names: the padding pass's pre-pad provenance (which
        feeds are batch-led), recorded into the compile exactly like
        run_multi's feed_list path."""
        fetch_names = self._fetch_names(fetch_list)
        compiled = self._resolve(fetch_names, sig_feed, batch_feed_names)
        from . import trace as _trace
        _trace.flight_recorder.record(
            'multi_dispatch', executor='ParallelExecutor',
            steps=int(steps), fetch_names=list(compiled.fetch_names),
            trace_id=getattr(_trace.current(), 'trace_id', None))
        fetches = compiled.run_multi(self._scope, {}, self._next_rng(),
                                     int(steps), scanned_feeds=scanned)
        if compiled.note_multi_compile(steps, scanned):
            self.compile_count += 1
        self.dispatch_count += 1
        self.steps_dispatched += int(steps)
        return fetches, compiled

    def _dispatch_eval_multi(self, fetch_list, feed=None, steps=None,
                             feed_list=None, reader=None):
        """Async front half of the SPMD run_eval_multi (the serving
        engine's dp>1 path): GSPMD-sharded K-eval-lots-per-dispatch
        scan, returning ``(stacked_fetches, reals, target, compiled,
        k)`` with NO host sync.  Ragged lots pad to the dp extent with
        masked samples exactly as run_multi's do.  ``reader=`` drains up
        to ``steps`` DISTINCT eval minibatches from the program's
        py_reader onto the feed_list path (so reader lots ride the same
        ragged dp-padding), mirroring Executor._dispatch_eval_multi."""
        import jax
        if reader is not None:
            from .dataflow import check_reader_args, drain_reader_feed_list
            check_reader_args('run_eval_multi', feed, feed_list, steps,
                              require_steps=True)
            feed_list = drain_reader_feed_list(self._main_program, reader,
                                               steps)
        else:
            _reject_reader_fed(self._main_program,
                               'ParallelExecutor.run_eval_multi')
        fetch_names = self._fetch_names(fetch_list)
        scanned = None
        if feed_list is not None:
            if feed is not None:
                raise ValueError('run_eval_multi: pass feed OR feed_list')
            if not feed_list:
                raise ValueError('run_eval_multi: feed_list is empty')
            per_step = [prepare_feed_arrays(dict(f)) for f in feed_list]
            steps = len(per_step)
            check_feed_list_names(per_step, 'run_eval_multi')
            normalize_trailing_feed_list(per_step)
            per_step, reals, target, batch_feed_names = \
                normalize_ragged_feed_list(per_step, self._pad_ragged)
            check_feed_list_uniform(per_step)
            compiled = self._resolve(fetch_names, per_step[0],
                                     batch_feed_names)
            scanned = {
                n: jax.device_put(stack_steps([fa[n] for fa in per_step]),
                                  compiled.scanned_sharding(n))
                for n in per_step[0]
            }
            feed_arrays = {}  # every feed name arrives via the scan
        else:
            if steps is None or int(steps) < 1:
                raise ValueError(
                    'run_eval_multi: steps must be >= 1, got %r'
                    % (steps, ))
            steps = int(steps)
            rpt = {}
            feed_arrays, real, target = self._pad_ragged(
                prepare_feed_arrays(dict(feed if feed is not None else {})),
                report=rpt)
            reals = [real] * steps if real != target else None
            compiled = self._resolve(fetch_names, feed_arrays,
                                     rpt.get('batch_names'))
        rng = self._next_rng()
        from . import trace as _trace
        _trace.flight_recorder.record(
            'eval_dispatch', executor='ParallelExecutor',
            steps=int(steps), fetch_names=list(compiled.fetch_names),
            trace_id=getattr(_trace.current(), 'trace_id', None))
        stacked = compiled.run_eval_multi(self._scope, feed_arrays, rng,
                                          steps, scanned_feeds=scanned)
        if compiled.note_eval_compile(steps, scanned):
            self.compile_count += 1
        self.dispatch_count += 1
        self.steps_dispatched += int(steps)
        return stacked, reals, target, compiled, steps

    def run_eval_multi(self, fetch_list, feed=None, steps=None,
                       feed_list=None, return_numpy=True, reader=None):
        """Run ``steps`` EVAL iterations as ONE GSPMD-sharded device
        dispatch and return EVERY iteration's fetches (the SPMD
        counterpart of Executor.run_eval_multi — dp>1 sharded serving).
        Same return convention: one [K, ...]-stacked entry per fetch,
        batch-led fetches over unequal ragged lots as per-step lists.
        ``reader=``: up to ``steps`` DISTINCT fresh eval minibatches
        drain from the program's py_reader per dispatch (the eval
        sweep's symmetric mode; drain contract as Executor's — tail on
        EOF mid-block, bucket-boundary push-back, EOFException when
        already exhausted)."""
        from .executor import convert_eval_fetches
        stacked, reals, target, compiled, k = self._dispatch_eval_multi(
            fetch_list, feed=feed, steps=steps, feed_list=feed_list,
            reader=reader)
        return convert_eval_fetches(stacked, reals, target, compiled, k,
                                    return_numpy)

    def run_decode_multi(self, feed=None, carry=None, steps=None,
                         decode=None):
        """K autoregressive greedy-decode steps as ONE GSPMD-sharded
        device dispatch over the whole slot batch (the SPMD counterpart
        of Executor.run_decode_multi — ISSUE 7).  The slot carry shards
        its slot dim over 'dp' (the slot count must be a multiple of
        the dp extent — the engine sizes its cache so), per-slot stop
        conditions are masked inside the scan, and the carry is donated
        on device so the distributed decode cache updates in place.
        Returns (carry', tokens [K, S], alive_in [K, S]), no host
        sync."""
        carry_out, toks, alive_in, _ = self._dispatch_decode_multi(
            feed=feed, carry=carry, steps=steps, decode=decode)
        return carry_out, toks, alive_in

    def _dispatch_decode_multi(self, feed=None, carry=None, steps=None,
                               decode=None):
        """Async front half of the SPMD run_decode_multi (ISSUE 9 —
        the engine's pipelined decode lane, mirroring
        Executor._dispatch_decode_multi): dispatch one K-step sharded
        decode scan against a carry whose leaves may be DEVICE-RESIDENT
        (the previous dispatch's donated output carry — scan N+1 chains
        onto scan N with no host round trip), returning (carry', tokens
        [K, S], alive_in [K, S], compiled) with NO host sync."""
        from .executor import normalize_decode_spec, \
            check_decode_carry, canonical_decode_carry
        _reject_reader_fed(self._main_program,
                           'ParallelExecutor.run_decode_multi')
        if carry is None or steps is None or decode is None:
            raise ValueError('run_decode_multi: carry=, steps= and '
                             'decode= are required')
        steps = int(steps)
        spec = normalize_decode_spec(decode)
        check_decode_carry(carry, spec, 'run_decode_multi')
        carry = canonical_decode_carry(carry)
        slots = int(np.shape(carry['token'])[0])
        if slots % self._dp_extent() != 0:
            raise ValueError(
                'run_decode_multi: %d slots do not divide over the dp '
                'extent %d — size the slot batch to a multiple of the '
                'mesh' % (slots, self._dp_extent()))
        fetch_names = self._fetch_names(
            [spec['logits']] + [f for _, f in spec['state']])
        sig_feed = dict(feed or {})
        sig_feed[spec['token']] = carry['token']
        sig_feed.update(carry['slots'])
        feed_arrays = prepare_feed_arrays(sig_feed)
        compiled = self._resolve(fetch_names, feed_arrays)
        const = {n: v for n, v in feed_arrays.items()
                 if n not in carry['slots'] and n != spec['token']}
        carry_sig = dict(carry['slots'])
        carry_sig[spec['token']] = carry['token']
        if compiled.note_decode_compile(steps, carry_sig):
            self.compile_count += 1
        from . import trace as _trace
        _trace.flight_recorder.record(
            'decode_dispatch', executor='ParallelExecutor', steps=steps,
            slots=slots,
            trace_id=getattr(_trace.current(), 'trace_id', None))
        carry_out, toks, alive_in = compiled.run_decode_multi(
            self._scope, const, self._next_rng(), steps, carry, spec)
        self.dispatch_count += 1
        self.steps_dispatched += steps
        return carry_out, toks, alive_in, compiled

    def _dispatch_chunk_prefill(self, feed=None, carry=None, aux=None,
                                chunk=None):
        """Async front half of the SPMD chunked prefill (ISSUE 14,
        mirroring Executor._dispatch_chunk_prefill): one C-token
        prefill advance of the chunk program over the dp-sharded slot
        batch, chained on the same device-resident carry the decode
        scans use.  Returns (carry', alive', compiled), no host
        sync."""
        from .executor import normalize_chunk_spec, check_chunk_aux, \
            canonical_decode_carry
        _reject_reader_fed(self._main_program,
                           'ParallelExecutor.run_chunk_prefill')
        if carry is None or aux is None or chunk is None:
            raise ValueError('run_chunk_prefill: carry=, aux= and '
                             'chunk= are required')
        spec = normalize_chunk_spec(chunk)
        carry = canonical_decode_carry(carry)
        slots = int(np.shape(carry['token'])[0])
        check_chunk_aux(aux, 'run_chunk_prefill', slots=slots)
        if slots % self._dp_extent() != 0:
            raise ValueError(
                'run_chunk_prefill: %d slots do not divide over the dp '
                'extent %d — size the slot batch to a multiple of the '
                'mesh' % (slots, self._dp_extent()))
        fetch_names = self._fetch_names([f for _, f in spec['state']])
        sig_feed = dict(feed or {})
        sig_feed.update(carry['slots'])
        feed_arrays = prepare_feed_arrays(sig_feed)
        compiled = self._resolve(fetch_names, feed_arrays)
        block_feed = {n: v for n, v in feed_arrays.items()
                      if n not in carry['slots']}
        width = int(np.shape(feed_arrays[spec['token']])[1])
        carry_sig = dict(carry['slots'])
        carry_sig[spec['token']] = feed_arrays[spec['token']]
        if compiled.note_chunk_compile(width, carry_sig):
            self.compile_count += 1
        from . import trace as _trace
        _trace.flight_recorder.record(
            'chunk_dispatch', executor='ParallelExecutor', width=width,
            slots=slots,
            trace_id=getattr(_trace.current(), 'trace_id', None))
        carry_out, ok = compiled.run_chunk_prefill(
            self._scope, block_feed, self._next_rng(), carry, aux, spec)
        self.dispatch_count += 1
        return carry_out, ok, compiled

    def cost_report(self):
        """Per-executable cost registry (ISSUE 6), the SPMD twin of
        Executor.cost_report(): every cached sharded executable's XLA
        cost/memory analysis captured under FLAGS_cost_accounting."""
        from .executor import collect_cost_report
        with self._cache_lock:
            blocks = list(self._cache.values())
        return collect_cost_report(blocks)

    def bcast_params(self):
        """Reference BCastParamsToDevices (parallel_executor.cc:169) — a
        no-op under GSPMD: replication is a sharding, not a copy loop."""
        pass
