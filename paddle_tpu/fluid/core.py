"""Runtime core for the TPU-native fluid framework.

This module plays the role of the reference's pybind ``core`` extension
(``paddle/fluid/pybind/pybind.cc``): places, dtype enums, Scope/Variable,
LoDTensor, and the bridge to the device runtime.  Here the device runtime is
JAX/XLA rather than CUDA: a ``Place`` resolves to a ``jax.Device``, and tensors
are ``jax.Array``s (host side: numpy).

Reference parity notes:
  - Place variant: paddle/fluid/platform/place.h:78
  - LoDTensor:     paddle/fluid/framework/lod_tensor.h:110
  - Scope:         paddle/fluid/framework/scope.h:39
"""

import os
import threading

import numpy as np

__all__ = [
    'CPUPlace', 'TPUPlace', 'CUDAPlace', 'Place', 'VarDesc', 'LoDTensor',
    'Scope', 'is_compiled_with_tpu', 'is_compiled_with_cuda',
    'get_tpu_device_count', 'EOFException',
]


class EOFException(Exception):
    """Raised by Executor.run when a program's reader is exhausted
    (reference: the C++ EOFException thrown by reader ops)."""
    pass

_jax = None
_jax_lock = threading.Lock()


def reconcile_platforms(jax):
    """Re-assert the JAX_PLATFORMS env var over the live jax config.

    JAX's documented contract is that the env var selects the platform,
    but ambient site configs may force-set ``jax.config.jax_platforms``
    (e.g. to 'axon,cpu') at interpreter start, overriding it — a process
    pinned to JAX_PLATFORMS=cpu then still dials (and, on a dead TPU
    tunnel, hangs on) the accelerator.  Compares only the priority
    platform so an 'axon,cpu' config under JAX_PLATFORMS=axon keeps its
    cpu fallback (host ops need the cpu backend)."""
    want = os.environ.get('JAX_PLATFORMS')
    if not want:
        return
    try:
        have = jax.config.jax_platforms or ''
        if have.split(',')[0] != want.split(',')[0]:
            jax.config.update('jax_platforms', want)
    except Exception:
        pass  # backends already initialized: leave the live config alone


def lazy_jax():
    """Import jax lazily so that pure graph construction needs no device."""
    global _jax
    if _jax is None:
        with _jax_lock:
            if _jax is None:
                import jax
                reconcile_platforms(jax)
                _jax = jax
    return _jax


# ----------------------------------------------------------------------------
# Places (paddle/fluid/platform/place.h)
# ----------------------------------------------------------------------------
class Place(object):
    """Base class of device placements."""

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.__dict__.items()))))


class CPUPlace(Place):
    def __repr__(self):
        return 'CPUPlace'

    def jax_device(self):
        jax = lazy_jax()
        return jax.devices('cpu')[0]


class TPUPlace(Place):
    """First-class TPU placement — the north-star addition vs the reference
    (which only has CPUPlace/CUDAPlace, place.h:36)."""

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __repr__(self):
        return 'TPUPlace(%d)' % self.device_id

    def jax_device(self):
        jax = lazy_jax()
        devs = [d for d in jax.devices() if d.platform != 'cpu']
        if not devs:  # CPU-only test environments
            devs = jax.devices()
        return devs[self.device_id % len(devs)]


class CUDAPlace(TPUPlace):
    """Compatibility alias: reference models built for CUDAPlace run on the
    default accelerator unchanged."""

    def __repr__(self):
        return 'CUDAPlace(%d)' % self.device_id


class CUDAPinnedPlace(CPUPlace):
    def __repr__(self):
        return 'CUDAPinnedPlace'


def is_compiled_with_tpu():
    try:
        jax = lazy_jax()
        return any(d.platform != 'cpu' for d in jax.devices())
    except Exception:
        return False


def is_compiled_with_cuda():
    # No CUDA in this build, ever (BASELINE.json north star).
    return False


def get_tpu_device_count():
    jax = lazy_jax()
    return len([d for d in jax.devices() if d.platform != 'cpu']) or len(
        jax.devices())


# ----------------------------------------------------------------------------
# Dtype enum (paddle/fluid/framework/framework.proto:97-131 VarType)
# ----------------------------------------------------------------------------
class VarDesc(object):
    class VarType(object):
        # data types
        BOOL = 0
        INT16 = 1
        INT32 = 2
        INT64 = 3
        FP16 = 4
        FP32 = 5
        FP64 = 6
        UINT8 = 20
        INT8 = 21
        BF16 = 22
        # var kinds
        LOD_TENSOR = 7
        SELECTED_ROWS = 8
        FEED_MINIBATCH = 9
        FETCH_LIST = 10
        STEP_SCOPES = 11
        LOD_RANK_TABLE = 12
        LOD_TENSOR_ARRAY = 13
        PLACE_LIST = 14
        READER = 15
        CHANNEL = 16
        RAW = 17
        TUPLE = 18


_DTYPE_TO_NP = {
    VarDesc.VarType.BOOL: np.bool_,
    VarDesc.VarType.INT16: np.int16,
    VarDesc.VarType.INT32: np.int32,
    VarDesc.VarType.INT64: np.int64,
    VarDesc.VarType.FP16: np.float16,
    VarDesc.VarType.FP32: np.float32,
    VarDesc.VarType.FP64: np.float64,
    VarDesc.VarType.UINT8: np.uint8,
    VarDesc.VarType.INT8: np.int8,
}
_NP_TO_DTYPE = {np.dtype(v): k for k, v in _DTYPE_TO_NP.items()}


def convert_np_dtype_to_dtype_(np_dtype):
    """numpy dtype (or string) -> VarType enum.  bfloat16 handled via ml_dtypes."""
    if isinstance(np_dtype, int):
        return np_dtype
    if np_dtype in ('bfloat16', 'bf16'):
        return VarDesc.VarType.BF16
    dtype = np.dtype(np_dtype)
    if dtype in _NP_TO_DTYPE:
        return _NP_TO_DTYPE[dtype]
    try:
        import ml_dtypes
        if dtype == np.dtype(ml_dtypes.bfloat16):
            return VarDesc.VarType.BF16
    except ImportError:
        pass
    raise ValueError('unsupported numpy dtype %s' % np_dtype)


def convert_dtype_to_np(dtype):
    """VarType enum (or string/np dtype) -> numpy dtype."""
    if dtype == VarDesc.VarType.BF16:
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if isinstance(dtype, int):
        return np.dtype(_DTYPE_TO_NP[dtype])
    if dtype in ('bfloat16', 'bf16'):
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dtype)


class PaddedSequence(object):
    """A LoD feed already lowered to device: padded [B, T, ...] data plus
    per-row lengths.  Produced by the double-buffer reader's prefetch
    thread (reference create_double_buffer_reader_op.cc moved batches to
    device ahead of the compute stream); consumed by
    executor.prepare_feed_arrays.  ``rows`` carries the OUTER level of a
    nested (2-level LoD) batch — sub-sequences per sequence — or None."""

    __slots__ = ('data', 'lengths', 'rows')

    def __init__(self, data, lengths, rows=None):
        self.data = data
        self.lengths = lengths
        self.rows = rows


# ----------------------------------------------------------------------------
# LoDTensor (paddle/fluid/framework/lod_tensor.h)
# ----------------------------------------------------------------------------
class LoDTensor(object):
    """A tensor with optional level-of-detail (nested variable-length
    sequence) offset metadata.

    Mirrors the reference's recursive-sequence-length semantics
    (framework/lod_tensor.h:58-110): ``lod`` is a list of offset vectors, one
    per nesting level, each starting at 0 and monotonically increasing; the
    last level's final offset equals dim 0 of the data.
    """

    def __init__(self, array=None, lod=None):
        self._array = None if array is None else np.asarray(array)
        self._lod = [list(l) for l in (lod or [])]

    def set(self, array, place=None):
        self._array = np.asarray(array)

    def set_lod(self, lod):
        self._lod = [list(l) for l in lod]

    def lod(self):
        return [list(l) for l in self._lod]

    def set_recursive_sequence_lengths(self, lengths):
        self._lod = []
        for level in lengths:
            offsets = [0]
            for n in level:
                offsets.append(offsets[-1] + n)
            self._lod.append(offsets)

    def recursive_sequence_lengths(self):
        return [[l[i + 1] - l[i] for i in range(len(l) - 1)]
                for l in self._lod]

    def has_valid_recursive_sequence_lengths(self):
        if not self._lod:
            return True
        for i, level in enumerate(self._lod):
            if not level or level[0] != 0:
                return False
            if any(level[j] > level[j + 1] for j in range(len(level) - 1)):
                return False
        if self._array is not None and self._lod:
            return self._lod[-1][-1] == self._array.shape[0]
        return True

    def shape(self):
        return list(self._array.shape) if self._array is not None else []

    def numpy(self):
        return np.asarray(self._array)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __repr__(self):
        return 'LoDTensor(shape=%s, lod=%s)' % (self.shape(), self._lod)


class LoDTensorArray(list):
    """Ordered list of LoDTensors — the host-side mirror of the
    LOD_TENSOR_ARRAY var type (reference pybind LoDTensorArray surface:
    append + indexing; produced/consumed by the tensor-array ops)."""

    def append(self, tensor):
        if not isinstance(tensor, LoDTensor):
            tensor = LoDTensor(np.asarray(tensor))
        list.append(self, tensor)


# ----------------------------------------------------------------------------
# SelectedRows (paddle/fluid/framework/selected_rows.h:32)
# ----------------------------------------------------------------------------
class SelectedRows(object):
    """Row-subset tensor {rows, value, height} — the host-side mirror of a
    sparse gradient (pybind.cc:233 surface: rows/set_rows/height/
    set_height/get_tensor)."""

    def __init__(self, rows=None, height=0):
        self._rows = list(rows) if rows is not None else []
        self._height = int(height)
        self._tensor = LoDTensor()

    def rows(self):
        return self._rows

    def set_rows(self, rows):
        self._rows = list(rows)

    def height(self):
        return self._height

    def set_height(self, height):
        self._height = int(height)

    def get_tensor(self):
        return self._tensor

    def to_dense(self):
        vals = self._tensor.numpy()
        out = np.zeros((self._height, ) + vals.shape[1:], vals.dtype)
        np.add.at(out, np.asarray(self._rows, np.int64), vals)
        return out

    def __repr__(self):
        return 'SelectedRows(n=%d, height=%d)' % (len(self._rows),
                                                  self._height)


# ----------------------------------------------------------------------------
# Scope (paddle/fluid/framework/scope.h:39)
# ----------------------------------------------------------------------------
class _ScopeVariable(object):
    """Runtime variable slot (framework/variable.h:26)."""

    __slots__ = ['_value']

    def __init__(self):
        self._value = None

    def get_tensor(self):
        if self._value is None:
            self._value = LoDTensor()
        return self._value

    def set_value(self, value):
        self._value = value

    def value(self):
        return self._value


class Scope(object):
    """Hierarchical name->Variable map with parent-chain lookup."""

    def __init__(self, parent=None):
        self._vars = {}
        self._parent = parent
        self._kids = []

    def var(self, name):
        v = self.find_var(name)
        if v is None:
            v = _ScopeVariable()
            self._vars[name] = v
        return v

    def find_var(self, name):
        if name in self._vars:
            return self._vars[name]
        if self._parent is not None:
            return self._parent.find_var(name)
        return None

    def new_scope(self):
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    def erase(self, names):
        for n in names:
            self._vars.pop(n, None)

    def local_var_names(self):
        return list(self._vars.keys())


_global_scope = Scope()


def global_scope():
    return _global_scope


# ----------------------------------------------------------------------------
# feed/fetch helpers (framework/feed_fetch_method.h parity)
# ----------------------------------------------------------------------------
def set_feed_variable(scope, value, name, idx=0):
    var = scope.var(name)
    if isinstance(value, LoDTensor):
        var.set_value(value)
    else:
        var.set_value(LoDTensor(np.asarray(value)))


def get_fetch_variable(scope, name, idx=0):
    var = scope.find_var(name)
    return None if var is None else var.value()
