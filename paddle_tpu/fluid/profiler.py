"""Profiler (reference: python/paddle/fluid/profiler.py:39-221).

The reference wraps a host EventList + CUPTI device tracer and dumps chrome
tracing JSON (tools/timeline.py).  On TPU the device tracer is the JAX/XLA
profiler (xplane); ``profiler(state, sorted_key, path)`` keeps the same
context-manager API: it records host-side per-run wall times and, when a
path is given, captures a JAX profiler trace viewable in TensorBoard /
Perfetto.
"""

import contextlib
import os
import threading
import time
from collections import defaultdict

from . import trace as _trace

__all__ = [
    'cuda_profiler', 'reset_profiler', 'profiler', 'start_profiler',
    'stop_profiler',
]

_profiler_state = {
    'enabled': False,
    'events': defaultdict(list),  # name -> [durations]
    'timeline': [],  # (name, start_s, dur_s) — tools/timeline.py source
    'trace_dir': None,
    'jax_trace_active': False,
    'start_time': None,
}
# record_event is called from background threads too (the serving
# engine's worker, the FeedPipeline's staging thread): the lock keeps
# appends atomic against a concurrent stop_profiler/reset_profiler
# swapping or iterating the event tables mid-profile
_record_lock = threading.Lock()

# subsystem metrics riding the sidecar: {source name: zero-arg snapshot
# fn}.  The serving engine registers here so a profiled serving window
# dumps queue depth / fill ratio / p50/p99 next to its timeline spans
# (tools/timeline.py renders the spans; the 'metrics' block carries the
# counters).  Sources returning None (e.g. a dead weakref) are skipped.
_metrics_sources = {}
# registrations come from user threads, engine GC finalizers, and the
# registry's loader concurrently: the uniquify scan + assign below is
# check-then-act and must be atomic or two same-named sources can both
# land on the bare key (the clobber the uniquify exists to prevent)
_sources_lock = threading.Lock()
# final snapshots of sources that unregistered MID-profile (the common
# `with profiler: with engine: ...` nesting stops the engine before
# stop_profiler collects) — without this the sidecar would lose them
_final_metrics = {}


def register_metrics_source(name, fn):
    """Register a snapshot source; returns the KEY it landed under.
    A name already held by a DIFFERENT live source is uniquified
    (``name#2``, ``name#3``, ...) instead of silently clobbered — two
    same-named engines stopped inside one profiler window must both
    keep their sidecar snapshot.  Callers unregister by the returned
    key."""
    with _sources_lock:
        key = name
        n = 1
        while (key in _metrics_sources
               and _metrics_sources[key] is not fn) \
                or (_profiler_state['enabled'] and key in _final_metrics):
            # _final_metrics holds snapshots of sources already STOPPED
            # in the ACTIVE profile window: a successor reusing their
            # name must not shadow them at collection time.  Outside a
            # window the leftover finals are dead (the next
            # start_profiler resets them) and must not push a fresh
            # source onto a #2 key forever.
            n += 1
            key = '%s#%d' % (name, n)
        _metrics_sources[key] = fn
        return key


def unregister_metrics_source(name, fn=None):
    """Drop a source.  Pass the registered fn to make the removal
    owner-checked: if another source has since taken the name (two
    engines registering as 'prod'), the survivor stays registered.
    Inside an active profile the source's last snapshot is kept for the
    session's sidecar."""
    with _sources_lock:
        if fn is not None and _metrics_sources.get(name) is not fn:
            return
        src = _metrics_sources.pop(name, None)
        take_final = src is not None and _profiler_state['enabled']
    if take_final:
        try:
            snap = src()
        except Exception:
            snap = None
        if snap is not None:
            # never clobber an earlier source's final snapshot: two
            # same-named engines stopped in one window keep BOTH rows
            # (the second lands as name#2)
            with _sources_lock:
                key = name
                n = 1
                while key in _final_metrics:
                    n += 1
                    key = '%s#%d' % (name, n)
                _final_metrics[key] = snap


def _collect_metrics():
    out = dict(_final_metrics)
    for name, fn in list(_metrics_sources.items()):
        try:
            snap = fn()
        except Exception:
            continue
        if snap is not None:
            out[name] = snap
    return out


def is_profiler_enabled():
    return _profiler_state['enabled']


def record_event(name, seconds, start=None):
    enabled = _profiler_state['enabled']
    if not enabled and not _trace.spans_enabled():
        return  # the hot path stays one dict lookup when both are off
    start_t = (time.time() - seconds) if start is None else start
    # mirror into the trace span log (no-op outside a trace.tracing()
    # window): every profiler event — executor runs, pipeline staging,
    # serving dispatches — lands in the Chrome-trace exporter's
    # per-thread lanes without a second instrumentation pass (ISSUE 6)
    _trace.record_span(name, start_t, seconds)
    if enabled:
        with _record_lock:
            _profiler_state['events'][name].append(seconds)
            _profiler_state['timeline'].append((name, start_t, seconds))


@contextlib.contextmanager
def record_block(name):
    if not _profiler_state['enabled']:
        yield
        return
    t0 = time.time()
    try:
        yield
    finally:
        record_event(name, time.time() - t0, start=t0)


def reset_profiler():
    with _record_lock:
        _profiler_state['events'] = defaultdict(list)
        _profiler_state['timeline'] = []
        _final_metrics.clear()


def start_profiler(state='All'):
    if _profiler_state['enabled']:
        return
    reset_profiler()  # each start opens a fresh session record
    _profiler_state['enabled'] = True
    _profiler_state['start_time'] = time.time()
    trace_dir = _profiler_state.get('trace_dir')
    if trace_dir and state in ('GPU', 'TPU', 'All'):
        try:
            import jax
            jax.profiler.start_trace(trace_dir)
            _profiler_state['jax_trace_active'] = True
        except Exception:
            _profiler_state['jax_trace_active'] = False


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    if not _profiler_state['enabled']:
        return
    _profiler_state['enabled'] = False
    if _profiler_state.get('jax_trace_active'):
        import jax
        jax.profiler.stop_trace()
        _profiler_state['jax_trace_active'] = False
    with _record_lock:
        # snapshot against a record_event already past the enabled check
        # on another thread (serving worker / pipeline stager)
        events = {n: list(d) for n, d in _profiler_state['events'].items()}
        _profiler_state['timeline'] = list(_profiler_state['timeline'])
    lines = ['%-40s %8s %12s %12s %12s' %
             ('Event', 'Calls', 'Total(s)', 'Min(s)', 'Max(s)')]
    rows = []
    for name, durs in events.items():
        rows.append((name, len(durs), sum(durs), min(durs), max(durs)))
    key_idx = {'calls': 1, 'total': 2, 'min': 3, 'max': 4}.get(
        sorted_key or 'total', 2)
    rows.sort(key=lambda r: r[key_idx], reverse=True)
    for r in rows:
        lines.append('%-40s %8d %12.6f %12.6f %12.6f' % r)
    report = '\n'.join(lines)
    if profile_path:
        try:
            with open(profile_path, 'w') as f:
                f.write(report)
        except OSError:
            pass
        # machine-readable sidecar: tools/timeline.py consumes this (the
        # reference dumps a profiler_pb2 proto for tools/timeline.py:115;
        # here the host record is JSON and device slices live in the
        # xplane capture referenced by trace_dir)
        try:
            import json
            with open(profile_path + '.events.json', 'w') as f:
                json.dump({
                    'host_events': [
                        {'name': n, 'start_s': s, 'dur_s': d}
                        for n, s, d in _profiler_state['timeline']],
                    'trace_dir': _profiler_state.get('trace_dir'),
                    'metrics': _collect_metrics(),
                }, f)
        except OSError:
            pass
    print(report)


@contextlib.contextmanager
def profiler(state, sorted_key=None, profile_path='/tmp/profile'):
    """Profile the enclosed region (reference profiler.py:136).

    state: 'CPU' (host timings only), 'GPU'/'TPU'/'All' (also capture a JAX
    device trace when profile_path names a directory)."""
    if state not in ('CPU', 'GPU', 'TPU', 'All'):
        raise ValueError("state must be 'CPU', 'GPU', 'TPU' or 'All'")
    if profile_path and os.path.isdir(profile_path):
        _profiler_state['trace_dir'] = profile_path
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)
        _profiler_state['trace_dir'] = None


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Kept for API parity; no CUDA in this build — delegates to the JAX
    trace when possible."""
    yield
