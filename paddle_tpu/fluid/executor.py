"""Executor: compiles whole program blocks to XLA and runs them on TPU.

The reference Executor interprets a ProgramDesc op-by-op, dispatching a
CPU/CUDA kernel per op with per-op InferShape (framework/executor.cc:321-339).
That design wastes a TPU: launch overhead per op, no fusion, host round-trips.
This Executor instead:

  1. partitions the block (currently: whole block) and traces every op's
     XLA lowering into ONE jitted function;
  2. threads persistable state (params, optimizer slots, BN stats) in and out
     functionally — the analog of in-place Scope variables;
  3. caches compiled executables keyed by (program version, feed signature,
     fetch list) — the analog of the reference's ExecutorPrepareContext +
     program cache (python/paddle/fluid/executor.py:283);
  4. falls back to eager op-by-op execution for programs containing host ops
     (save/load/print/reader) — those run unfused but with identical
     semantics.

API parity: Executor(place), run(program, feed, fetch_list, ...) matching
python/paddle/fluid/executor.py:256.
"""

import threading

import numpy as np

from . import core
from . import flags
from .framework import default_main_program, Variable
from .shape_policy import SEQ_BUCKET, bucketed_len
from ..ops import registry


def _check_nan_inf(pairs, where):
    """Post-execution NaN/Inf scan (reference FLAGS_check_nan_inf,
    framework/operator.cc): raises naming the first offending variable.
    The in-jit half is jax_debug_nans (toggled by the flag's setter),
    which attributes failures to the producing primitive."""
    for name, val in pairs:
        try:
            arr = np.asarray(val)
        except Exception:
            continue
        if arr.dtype.kind == 'f' and not np.all(np.isfinite(arr)):
            raise RuntimeError(
                'check_nan_inf: %s %r contains NaN/Inf' % (where, name))

__all__ = ['Executor', 'global_scope', 'scope_guard', '_switch_scope',
           'fetch_var']


def fetch_var(name, scope=None, return_numpy=True):
    """Fetch a (typically persistable) variable's value straight from a
    scope without running a program (reference executor.py:174)."""
    assert isinstance(name, str)
    if scope is None:
        scope = global_scope()
    var = scope.find_var(name)
    assert var is not None, (
        'Cannot find ' + name + ' in scope. Perhaps you need to make the'
        ' variable persistable by using var.persistable = True in your'
        ' program.')
    value = var.value()
    if return_numpy:
        return as_numpy(value)
    if not isinstance(value, core.LoDTensor):
        value = core.LoDTensor(np.asarray(value))
    return value

_scope_stack = [core.global_scope()]


def global_scope():
    """The active scope: scope_guard swaps it, like the reference's
    _switch_scope (python/paddle/fluid/executor.py:41-63)."""
    return _scope_stack[-1]


def _current_scope():
    return _scope_stack[-1]


def _switch_scope(scope):
    _scope_stack[-1] = scope
    return _scope_stack[-1]


import contextlib


@contextlib.contextmanager
def scope_guard(scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


def _is_host_op(op):
    # host ops (save/load/print/readers) register in the host-op registry;
    # any op with a host impl forces the eager (unfused) execution path
    return registry.is_host_op_type(op.type)


def as_numpy(value):
    if isinstance(value, core.LoDTensor):
        return value.numpy()
    return np.asarray(value)


def _pop_readers_into_feed(program, feed, place=None):
    """For each read op, pop one minibatch from its py_reader queue and
    inject it as feeds (reference: reader ops produce LoDTensors inside the
    interpreter loop; here data stays ahead of the compiled step).  Raises
    core.EOFException when a reader is exhausted."""
    for op in program.global_block().ops:
        if op.type != 'read':
            continue
        from .layers import io as layers_io
        reader_name = op.input('Reader')[0]
        feeder = layers_io.get_reader_feeder(reader_name)
        if feeder is None:
            raise RuntimeError('no py_reader registered for %r' %
                               reader_name)
        if place is not None:
            # bind the prefetch target to the executor CONSUMING this
            # reader (per-feeder, so an interleaved CPU eval executor
            # can't re-route a TPU train reader's staging)
            feeder._executor_place = place
        batch = feeder.pop()
        if batch is None:
            raise core.EOFException(
                'reader %r is exhausted — call reader.reset() and '
                'reader.start() for the next pass' % reader_name)
        for name, value in zip(op.output('Out'), batch):
            feed[name] = value


def prepare_feed_arrays(feed):
    """Normalize a user feed dict: LoD feeds lower to padded [B, T, ...]
    plus a ``<name>@SEQLEN`` lengths entry (SURVEY §5.7); device arrays
    pass through untouched.  Shared by Executor and ParallelExecutor."""
    import jax
    feed_arrays = {}
    for name, value in feed.items():
        if isinstance(value, core.PaddedSequence):
            # already padded + device-staged by a double-buffer reader
            feed_arrays[name] = value.data
            feed_arrays[name + registry.SEQLEN_SUFFIX] = value.lengths
            if value.rows is not None:
                feed_arrays[name + registry.ROWS_SUFFIX] = value.rows
        elif isinstance(value, core.LoDTensor) and value.lod():
            padded, lengths = _lod_to_padded(value)
            feed_arrays[name] = padded
            feed_arrays[name + registry.SEQLEN_SUFFIX] = lengths
            lod = value.lod()
            if len(lod) >= 2:
                # nested sequence: also carry the outer level (number of
                # sub-sequences per top-level sequence)
                outer = np.asarray(lod[0], np.int64)
                feed_arrays[name + registry.ROWS_SUFFIX] = (
                    outer[1:] - outer[:-1]).astype(np.int32)
        elif isinstance(value,
                        (core.LoDTensor, core.SelectedRows, jax.Array)):
            feed_arrays[name] = value
        else:
            feed_arrays[name] = np.asarray(value)
    return feed_arrays


def validate_feed(program, feed_arrays):
    """Fail fast with the var name and dims when a feed does not match its
    data-layer declaration (the analog of the reference DataFeeder checks,
    data_feeder.py:29)."""
    block = program.block(0)
    for name, value in feed_arrays.items():
        if name.endswith((registry.SEQLEN_SUFFIX, registry.ROWS_SUFFIX)):
            continue
        if name == registry.SAMPLE_MASK_NAME:
            continue  # executor-injected ragged-batch mask, not a data var
        if isinstance(value, core.SelectedRows):
            continue  # row-subset feeds carry their own height metadata
        var = block.vars.get(name)
        if var is None or not getattr(var, 'shape', None):
            continue
        shape = tuple(var.shape)
        got = getattr(value, 'shape', None)  # no device->host copy
        if callable(got):  # core.LoDTensor exposes shape() as a method
            got = got()
        got = tuple(got) if got is not None else tuple(
            np.shape(as_numpy(value)))
        lod = getattr(var, 'lod_level', 0) or 0
        ranks = (len(shape), ) if not lod else (len(shape) + 1, len(shape))
        if len(got) not in ranks:
            raise ValueError(
                'feed %r: expected rank %s (declared shape %s%s), got '
                'shape %s' % (name, ranks[0], shape,
                              ', lod_level=%d' % lod if lod else '', got))
        # declared dims must match aligned from the right (leading
        # batch/time dims are free; -1 dims are wildcards)
        for want, have in zip(reversed(shape), reversed(got)):
            if want is not None and want > 0 and want != have:
                raise ValueError(
                    'feed %r: dim mismatch, declared shape %s%s but got '
                    'shape %s' % (name, shape,
                                  ' (lod_level=%d)' % lod if lod else '',
                                  got))


def feed_signature(feed_arrays):
    import jax

    def _sig_of(v):
        if isinstance(v, jax.Array):
            return tuple(v.shape), str(v.dtype)
        if isinstance(v, core.SelectedRows):
            t = v.get_tensor().numpy()
            return ('sr', ) + tuple(np.shape(t)), str(t.dtype)
        a = as_numpy(v)
        return tuple(np.shape(a)), str(a.dtype)

    return tuple((n, ) + _sig_of(v) for n, v in sorted(feed_arrays.items()))


def check_feed_list_uniform(per_step):
    """lax.scan needs a uniform per-step structure: every prepared batch
    must share feed_list[0]'s names, shapes AND dtypes (a mixed-dtype
    stack would silently promote the whole scanned axis past the
    compiled block's feed signature).  Uniformity is exactly 'same
    feed_signature', so reuse it."""
    sig0 = feed_signature(per_step[0])
    for i, fa in enumerate(per_step[1:], 1):
        if feed_signature(fa) != sig0:
            raise ValueError(
                'run_multi: feed_list[%d] differs in names, shapes or '
                'dtypes from feed_list[0] — all batches must '
                'share one shape bucket (pad to it, or group '
                'batches by bucket)' % i)


def check_feed_list_names(per_step, what):
    """Every lot must share feed_list[0]'s NAME set before any
    cross-lot inference walks those names over the others (shared by
    run_multi and run_eval_multi on both executors)."""
    names0 = set(per_step[0])
    for i, fa in enumerate(per_step[1:], 1):
        if set(fa) != names0:
            raise ValueError(
                '%s: feed_list[%d] differs in names from feed_list[0]'
                % (what, i))


def normalize_trailing_feed_list(per_step):
    """Trailing-dim twin of normalize_ragged_feed_list (ISSUE 5): lots
    whose SEQ feeds disagree on the padded time extent re-quantize onto
    the shared seq-len ladder instead of failing the scan's uniformity
    check.  Only feeds carrying a ``<name>@SEQLEN`` lengths companion
    participate — their lowerings mask by real length, so zero-padding
    axis 1 up to ``bucketed_len(max extent)`` is exactly the fill
    ``_lod_to_padded`` already applies per batch (a dense feed with no
    lengths has no masking contract, and stays an error).  Mutates and
    returns ``per_step``; device-staged arrays only round-trip the host
    on the disagreeing (ragged) path."""
    names0 = per_step[0]
    for name in list(names0):
        if name.endswith((registry.SEQLEN_SUFFIX, registry.ROWS_SUFFIX)):
            continue
        if (name + registry.SEQLEN_SUFFIX) not in names0:
            continue
        extents = []
        for fa in per_step:
            v = fa[name]
            shape = v.shape() if isinstance(v, core.LoDTensor) \
                else np.shape(v)
            if len(shape) < 2:
                extents = None
                break
            extents.append(int(shape[1]))
        if not extents or len(set(extents)) == 1:
            continue
        t = _bucketed_len(max(extents))
        for fa, e in zip(per_step, extents):
            if e == t:
                continue
            arr = np.asarray(fa[name].numpy()
                             if isinstance(fa[name], core.LoDTensor)
                             else fa[name])
            pad = [(0, 0)] * arr.ndim
            pad[1] = (0, t - e)
            fa[name] = np.pad(arr, pad)
    return per_step


def prepare_feed_list(feed_list):
    """Normalize a run_multi feed_list: one prepared feed dict per
    iteration, uniform across steps.  Returns (steps, per_step).
    (ParallelExecutor.run_multi composes the pieces itself — it must
    pad ragged lots between preparation and the uniformity check.)"""
    if not feed_list:
        raise ValueError('run_multi: feed_list is empty')
    per_step = [prepare_feed_arrays(dict(f)) for f in feed_list]
    check_feed_list_names(per_step, 'run_multi')
    normalize_trailing_feed_list(per_step)
    check_feed_list_uniform(per_step)
    return len(per_step), per_step


def stack_steps(vals):
    """Stack per-iteration feeds along a new leading K axis for the
    scanned dispatch.  Device-resident values (the double-buffer
    prefetch form) stack ON DEVICE — np.stack would drag each batch
    back through the host only to re-upload the whole epoch."""
    import jax
    import jax.numpy as jnp
    if all(isinstance(v, jax.Array) for v in vals):
        return jnp.stack(vals)
    return np.stack([np.asarray(v) for v in vals])


def fetch_batch_led(compiled, n):
    """The trace's batch-led provenance side channel, defaulting to
    all-False before the first trace: the ONE reading of
    ``_fetch_batch_led`` shared by every consumer that trims padded
    rows (convert_eval_fetches, ParallelExecutor._convert_fetches, the
    serving engine's per-request slicer) — so a change to the side
    channel's convention has a single place to land."""
    return getattr(compiled, '_fetch_batch_led', None) or [False] * n


def convert_eval_fetches(stacked, reals, target, compiled, steps,
                         return_numpy):
    """Host-side back half of run_eval_multi (shared by Executor and
    ParallelExecutor): convert each [K, ...]-stacked fetch, trimming
    BATCH-LED fetches (per the trace's provenance side channel) from the
    padded row count ``target`` back to the per-step real counts.  Equal
    real counts trim as one slice (still a stacked array); unequal ones
    come back as a list of K per-step arrays."""
    led = fetch_batch_led(compiled, len(stacked))
    out = []
    for arr, is_led in zip(stacked, led):
        a = np.asarray(arr)
        if reals is not None and is_led and a.ndim >= 2 \
                and a.shape[1] == target:
            if len(set(reals)) == 1:
                a = a[:, :reals[0]]
            else:
                per = [a[i][:reals[i]] for i in range(steps)]
                out.append(per if return_numpy else
                           [core.LoDTensor(p) for p in per])
                continue
        out.append(a if return_numpy else core.LoDTensor(a))
    return out


def collect_cost_report(compiled_blocks):
    """Flatten compiled blocks' captured cost entries into the
    ``cost_report()`` list form shared by Executor and ParallelExecutor
    (ISSUE 6): one record per analyzed executable — kind, steps, XLA
    cost-analysis FLOPs (total and per step), bytes accessed, and the
    memory-analysis buffer sizes.  Entries exist only for executables
    dispatched under FLAGS_cost_accounting."""
    out = []
    for compiled in compiled_blocks:
        for key, entry in compiled.cost_entries().items():
            if entry is None:
                continue
            rec = dict(entry)
            rec['key'] = repr(key)
            out.append(rec)
    return out


def normalize_decode_spec(decode):
    """Validate + normalize the ``decode=`` argument shared by BOTH
    executors' ``run_decode_multi`` (ISSUE 7).  The spec names the
    autoregressive wiring of a STEP program:

      token:   the feed carrying the current token ([S, 1] int)
      logits:  the fetch (Variable or name) whose argmax is the next
               token ([S, vocab] — the greedy-decode selection)
      state:   ordered (feed_name, fetch) pairs — each step the fetch's
               value becomes the feed's next value (the KV/hidden slot
               state threading through the scan carry)
      context: feed names that live in the slot carry but never update
               (per-slot read-only state, e.g. encoder outputs)
      end_id:  the EOS token id (the per-slot stop condition, masked
               inside the scan next to the per-slot step budget)
    """
    if not isinstance(decode, dict):
        raise ValueError('decode= must be a dict (token/logits/state/'
                         'end_id), got %r' % (type(decode), ))
    missing = [k for k in ('token', 'logits', 'state', 'end_id')
               if k not in decode]
    if missing:
        raise ValueError('decode= is missing %s' % missing)

    def name_of(v):
        return v.name if isinstance(v, Variable) else str(v)

    state = decode['state']
    if isinstance(state, dict):
        state = list(state.items())
    state = [(str(feed_n), name_of(fetch)) for feed_n, fetch in state]
    if not state:
        raise ValueError('decode= needs at least one state pair — a '
                         'stateless step function has nothing to carry '
                         'between decode steps')
    return {
        'token': str(decode['token']),
        'logits': name_of(decode['logits']),
        'state': tuple(state),
        'context': tuple(str(n) for n in decode.get('context', ())),
        'end_id': int(decode['end_id']),
    }


def canonical_decode_carry(carry):
    """Canonicalize the decode carry's array leaves to jax's dtype
    rules ONCE on the way in (shared by both executors'
    run_decode_multi).  Without jax x64, a host int64 token would
    compile one executable on the first dispatch and a DIFFERENT one
    (int32 — the scan's own output dtype) on every later dispatch:
    the signature must be stable across the carry round trip."""
    import jax.numpy as jnp

    def c(v):
        return v if hasattr(v, 'devices') else jnp.asarray(v)

    return {'slots': {n: c(v) for n, v in carry['slots'].items()},
            'token': c(carry['token']), 'alive': c(carry['alive']),
            'remaining': c(carry['remaining'])}


def check_decode_carry(carry, spec, what):
    """Fail fast when a decode carry does not match its spec: the slot
    dict must cover exactly the state + context feeds, and the
    token/alive/remaining leaves must be present (shared by both
    executors' run_decode_multi)."""
    if not isinstance(carry, dict):
        raise ValueError('%s: carry must be a dict, got %r'
                         % (what, type(carry)))
    missing = [k for k in ('slots', 'token', 'alive', 'remaining')
               if k not in carry]
    if missing:
        raise ValueError('%s: carry is missing %s' % (what, missing))
    want = set(n for n, _ in spec['state']) | set(spec['context'])
    have = set(carry['slots'])
    # @SEQLEN/@ROWS companions of context feeds ride along untouched
    extra = {n for n in have - want
             if not n.endswith((registry.SEQLEN_SUFFIX,
                                registry.ROWS_SUFFIX))}
    if want - have or extra:
        raise ValueError(
            '%s: carry slots %s do not match the decode spec (missing '
            '%s, unexpected %s)' % (what, sorted(have),
                                    sorted(want - have), sorted(extra)))


def normalize_chunk_spec(chunk):
    """Validate + normalize the ``chunk=`` argument shared by BOTH
    executors' ``run_chunk_prefill`` (ISSUE 14).  The spec names the
    chunked-prefill wiring of a CHUNK program — the C-token-block form
    of a generation model's prompt consumption:

      token:    the feed carrying one [S, C, 1] token block per slot
      len:      optional per-slot real-length feed ([S, 1] float — the
                transformer family masks its in-block scatter with it;
                the engine always ALSO injects the token feed's @SEQLEN
                companion for sequence-op masking)
      state:    ordered (step_feed_name, chunk_fetch) pairs — the
                chunk program's advanced value for every decode-state
                slab (must cover the decode spec's state feeds exactly)
      start_id: the BOS token written into finishing slots' carry
    """
    if not isinstance(chunk, dict):
        raise ValueError('chunk= must be a dict (token/len/state/'
                         'start_id), got %r' % (type(chunk), ))
    missing = [k for k in ('token', 'state', 'start_id')
               if k not in chunk]
    if missing:
        raise ValueError('chunk= is missing %s' % missing)

    def name_of(v):
        return v.name if isinstance(v, Variable) else str(v)

    state = chunk['state']
    if isinstance(state, dict):
        state = list(state.items())
    state = [(str(feed_n), name_of(fetch)) for feed_n, fetch in state]
    if not state:
        raise ValueError('chunk= needs at least one state pair — a '
                         'chunk that advances no slab is a no-op')
    return {
        'token': str(chunk['token']),
        'len': (str(chunk['len'])
                if chunk.get('len') is not None else None),
        'state': tuple(state),
        'start_id': int(chunk['start_id']),
    }


def check_chunk_aux(aux, what, slots=None):
    """Fail fast when a chunk dispatch's per-slot aux leaves are
    malformed (shared by both executors' run_chunk_prefill): the
    active/finish masks and the finishing-slot step budget must all be
    present, one-dimensional, and ``slots`` long — a transposed or
    scalar leaf would otherwise surface as an opaque jit broadcasting
    error (or silently wrong finish masking) inside the chunk
    kernel."""
    if not isinstance(aux, dict):
        raise ValueError('%s: aux must be a dict, got %r'
                         % (what, type(aux)))
    missing = [k for k in ('active', 'finish', 'budget')
               if k not in aux]
    if missing:
        raise ValueError('%s: aux is missing %s' % (what, missing))
    for k in ('active', 'finish', 'budget'):
        shape = np.shape(aux[k])
        if len(shape) != 1 or \
                (slots is not None and int(shape[0]) != int(slots)):
            raise ValueError(
                '%s: aux[%r] must be a 1-D per-slot vector%s, got '
                'shape %s' % (what, k,
                              ' of length %d' % slots
                              if slots is not None else '', shape))


def _reject_reader_fed(program, what):
    """The PLAIN-FEED multi paths never compose with py_reader-fed
    programs: resolving would pop exactly ONE minibatch and the K-step
    loop would train on it K times with no signal (the reference
    multi-iteration loop, executor.cc:321-339, pulls fresh data every
    iteration).  run_multi(reader=..., steps=K) is the composing form:
    it drains K DISTINCT batches per dispatch (fluid.dataflow)."""
    prog = program if program is not None else default_main_program()
    if any(op.type == 'read' for op in prog.global_block().ops):
        # each multi path names ITS OWN reader= mode (train and eval
        # drains are symmetric since ISSUE 4's run_eval_multi reader=)
        composing = ('run_eval_multi(reader=..., steps=K)'
                     if 'eval' in what else
                     'run_multi(reader=..., steps=K)')
        raise RuntimeError(
            '%s does not compose with py_reader-fed programs through '
            'feed=/feed_list= — pass the reader (%s drains K fresh '
            'batches per dispatch), feed the batches explicitly, or '
            'use run() per step' % (what, composing))
    return prog


# The seq-len ladder policy lives in shape_policy so the serving
# engine's trailing ladder and the feed_list normalization share ONE
# tuning knob (ISSUE 5); the old private names stay as aliases.
_SEQ_BUCKET = SEQ_BUCKET
_bucketed_len = bucketed_len


def _lod_to_padded(lt, bucket=_SEQ_BUCKET):
    """Concatenated LoD tensor -> (padded [B, T, ...], lengths [B]).

    T is bucketed so recompiles are bounded (the static-shape answer to
    LoD's no-padding design, SURVEY §5.7; policy in _bucketed_len)."""
    data = lt.numpy()
    offsets = np.asarray(lt.lod()[-1], np.int64)
    lengths = (offsets[1:] - offsets[:-1]).astype(np.int32)
    b = len(lengths)
    max_len = int(lengths.max()) if b else 0
    t = _bucketed_len(max_len, bucket)
    out = np.zeros((b, t) + data.shape[1:], data.dtype)
    if b and len(data):
        # vectorized scatter: row i gets data[offsets[i]:offsets[i+1]]
        row = np.repeat(np.arange(b), lengths)
        pos = np.arange(len(data)) - np.repeat(offsets[:-1], lengths)
        out[row, pos] = data
    return out, lengths


def _to_device_value(value, var_desc, device):
    import jax
    if isinstance(value, core.SelectedRows):
        return value  # host-domain value; consumed by host ops as-is
    if isinstance(value, jax.Array):
        # already on device (the common case for state after step 1):
        # avoid the device->host->device round trip
        try:
            if device in value.devices():
                return value
        except Exception:
            pass
        return jax.device_put(value, device)
    if isinstance(value, core.LoDTensor):
        value = value.numpy()
    arr = np.asarray(value)
    if var_desc is not None and arr.dtype != var_desc.np_dtype:
        # feeding python lists/floats: trust the declared dtype
        if np.issubdtype(arr.dtype, np.floating) and np.issubdtype(
                var_desc.np_dtype, np.floating):
            arr = arr.astype(var_desc.np_dtype)
    return jax.device_put(arr, device)


class _CompiledBlock(object):
    """One jitted XLA executable for a (program, feed-sig, fetch) triple."""

    def __init__(self, program, block_idx, feed_names, fetch_names, place,
                 scope):
        import jax
        self.program = program
        self.block = program.block(block_idx)
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.place = place
        block = self.block

        # read ops are satisfied on the host before the jitted call (their
        # outputs arrive as feeds), keeping the compute path fully fused
        ops = [op for op in block.ops
               if op.type not in ('feed', 'fetch', 'read')]
        self.ops = ops

        # Walk program order to find which persistable vars must come from
        # the scope (read-before-write) and which are written.
        defined = set(self.feed_names)
        state_in = []
        state_out = []

        def threadable(v):
            # SELECTED_ROWS-typed vars (sparse tables, row-subset grads)
            # live in the host domain: host ops manage them via the scope
            # directly, never as threaded jit state
            return (v is not None and v.persistable and
                    v.type != core.VarDesc.VarType.SELECTED_ROWS)

        for op in ops:
            reads = list(op.input_arg_names)
            if op.type in ('conditional_block', 'ifelse', 'switch_case'):
                # blended control flow READS every written var's old
                # value (the cond-false blend), so a startup-initialized
                # persistable updated in a branch must arrive as state_in
                reads += list(op.output_arg_names)
            for name in reads:
                if name in defined or name in state_in:
                    continue
                if threadable(block._find_var_recursive(name)):
                    state_in.append(name)
                    defined.add(name)
            for name in op.output_arg_names:
                v = block._find_var_recursive(name)
                if threadable(v) and name not in state_out:
                    state_out.append(name)
                defined.add(name)
        # fetching a persistable var that no op writes still needs its value
        for name in self.fetch_names:
            if name not in defined:
                if threadable(block._find_var_recursive(name)):
                    state_in.append(name)
                    defined.add(name)
        self.state_in = state_in
        self.state_out = state_out
        # split read-write from read-only: only RW buffers may be donated,
        # otherwise XLA can alias a read-only input (e.g. the LR scalar) to
        # an output and delete the buffer the scope still references
        self.state_rw = [n for n in state_in if n in set(state_out)]
        self.state_ro = [n for n in state_in if n not in set(state_out)]

        fetch_names_ = self.fetch_names
        state_out_ = state_out
        # filled by _SpmdCompiledBlock before its first trace; consulted by
        # mesh-aware lowerings (ring attention) at trace time
        self._spmd_ref = {'mesh': None, 'batch_axis': None}
        spmd_ref = self._spmd_ref

        def fn(state_rw, state_ro, feeds, rng):
            env = {}
            env.update(state_rw)
            env.update(state_ro)
            env.update(feeds)
            ctx = registry.LoweringContext(block, env, rng_key=rng,
                                           place=place,
                                           mesh=spmd_ref['mesh'],
                                           batch_axis=spmd_ref['batch_axis'])
            mask = feeds.get(registry.SAMPLE_MASK_NAME)
            if mask is not None:
                # ragged-batch provenance roots: the feeds the PADDING
                # treated as batch-led (recorded pre-padding, where an
                # aux feed whose rows merely coincide with the padded
                # size is still distinguishable), falling back to the
                # dim-0 shape match.  run_op propagates from here;
                # state (params) is never batch-led.
                declared = getattr(self, '_batch_feed_names', None)
                if declared is not None:
                    ctx.batch_led = {n for n in feeds if n in declared}
                else:
                    ctx.batch_led = {
                        n for n, v in feeds.items()
                        if getattr(v, 'ndim', 0) >= 1
                        and v.shape[0] == mask.shape[0]}
                ctx.batch_tainted = set(ctx.batch_led)
            for op in ops:
                registry.run_op(ctx, op)
            registry.check_cond_uninit(ctx, fetch_names_, 'fetch')
            # NOTE a persistable var assigned only inside a conditional
            # block cannot reach here cond-uninit: the state scan counts
            # blended control flow's outputs as READS, so the var is
            # state_in — either the scope lacks it (_state_from_scope
            # raises 'not initialized') or its real value is in env and
            # the blend keeps it.  No zeros ever persist.
            new_state = {n: env[n] for n in state_out_ if n in env}
            fetches = [env[n] for n in fetch_names_]
            # trace-time side channel: which fetches are batch-led, so
            # the ragged-batch executors trim ONLY those back to the
            # real row count (a parameter fetch whose dim 0 coincides
            # with the padded batch size must come back whole)
            self._fetch_batch_led = [n in ctx.batch_led
                                     for n in fetch_names_]
            return new_state, fetches

        self._fn = fn
        self._fetch_batch_led = None  # set at first trace
        donate = (0, ) if self.state_rw else ()
        self._jit = jax.jit(fn, donate_argnums=donate)

        # eager-path release plan (memory_optimize transpiler): names the
        # pass marked releasable, positioned at their last use over THIS
        # executable's op list and filtered against what must stay alive
        # to the end
        self._eager_release = {}
        allowed = getattr(program, '_releasable', None)
        if allowed:
            keep = (set(self.fetch_names) | set(state_out) |
                    set(state_in))
            last = {}
            for i, op in enumerate(ops):
                for n in op.input_arg_names:
                    last[n] = i
                for n in op.output_arg_names:
                    last[n] = i
            rel = {}
            for n, i in last.items():
                if n in allowed and n not in keep:
                    rel.setdefault(i, []).append(n)
            self._eager_release = rel

    def _run_eager(self, scope, state_rw, state_ro, feeds, rng):
        """Unfused op-by-op execution for blocks containing host ops
        (save/load/print/readers) — identical semantics, no jit."""
        env = {}
        env.update(state_rw)
        env.update(state_ro)
        env.update(feeds)
        ctx = registry.LoweringContext(
            self.block, env, rng_key=rng, place=self.place)
        ctx.scope = scope
        check_nan = flags.FLAGS.check_nan_inf
        for op_idx, op in enumerate(self.ops):
            host_impl = registry.get_host_op(op.type)
            if host_impl is not None:
                # host ops bypass run_op: apply the may-read-before-
                # write check here (a save/print of a cond-uninit var
                # is exactly the reference's uninitialized-read error)
                registry.check_cond_uninit(ctx, op.input_arg_names,
                                           'host op %r' % op.type)
                host_impl(ctx, op, scope)
                # ...and an unconditional host-op WRITE (load/
                # load_combine) covers the name, same as run_op's rule
                for n in op.output_arg_names:
                    ctx.cond_uninit.discard(n)
            else:
                registry.run_op(ctx, op)
            if check_nan:
                # eager path gets reference-style per-op attribution
                _check_nan_inf(
                    [(n, env[n]) for n in op.output_arg_names if n in env],
                    'output of op %r' % op.type)
            # memory_optimize release plan: drop vars past their last use
            # so the eager env's peak live set matches true liveness
            for n in self._eager_release.get(op_idx, ()):
                env.pop(n, None)
        registry.check_cond_uninit(ctx, self.fetch_names, 'fetch')
        new_state = {n: env[n] for n in self.state_out if n in env}
        fetches = [env[n] for n in self.fetch_names]
        return new_state, fetches

    def _state_from_scope(self, scope, names, to_value, cache_back=False):
        import jax
        state = {}
        for name in names:
            var = scope.find_var(name)
            if var is None or var.value() is None:
                raise RuntimeError(
                    'persistable var %r is not initialized in scope — '
                    'did you run the startup program?' % name)
            raw = var.value()
            val = to_value(raw, self.block._find_var_recursive(name))
            if cache_back and isinstance(val, jax.Array) \
                    and not isinstance(raw, jax.Array):
                # host-resident READ-ONLY state (e.g. params
                # load_inference_model just read from disk) stays
                # device-resident after the first staging: run() never
                # writes state_ro back, so without this every inference
                # call re-uploaded ~all params — ~10ms tunnel latency
                # PER ARRAY made a 25ms ResNet-18 eval take 1.7s (r5).
                # RW state must NOT be cached here: its staged buffer
                # is donated into the jit, and caching it would leave
                # the scope pointing at deleted buffers if the step
                # raises before the post-run write-back.
                lod = raw.lod() if isinstance(raw, core.LoDTensor) else None
                if not lod:
                    var.set_value(val)
            state[name] = val
        return state

    def _materialize_args(self, scope, feed_values, cache_ro=False):
        """Device-stage the jit/eager call's arguments: threaded scope
        state and feeds (shared by run() and Executor.memory_analysis —
        the stats must describe the executable run() executes).
        cache_ro: run()-only — memory_analysis must stay side-effect
        free on the scope."""
        device = self.place.jax_device()
        to_value = lambda v, desc: _to_device_value(v, desc, device)
        state_rw = self._state_from_scope(scope, self.state_rw, to_value)
        state_ro = self._state_from_scope(scope, self.state_ro, to_value,
                                          cache_back=cache_ro)
        feeds = {
            n: _to_device_value(v, self.block._find_var_recursive(n), device)
            for n, v in feed_values.items()
        }
        return state_rw, state_ro, feeds

    # shared by every compiled block: entry inserts and cost_entries()
    # snapshots race between the dispatch thread and a metrics/bench
    # caller — one module lock keeps the dict copy coherent (held only
    # around dict ops, never across the AOT analysis compile)
    _COST_LOCK = threading.Lock()

    def _capture_cost(self, kind, key, jitted, args, steps=1):
        """Per-executable cost accounting (ISSUE 6): under
        FLAGS_cost_accounting, AOT-analyze ``jitted`` once per cache
        key (two racing first dispatches may both analyze; the result
        is identical and one wins the insert) and remember XLA's own
        FLOPs/bytes — the MFU/HBM ground truth behind
        Executor.cost_report().  Runs BEFORE the dispatch (the abstract
        twins never touch the soon-to-be-donated buffers); a backend
        without cost analysis caches None and never retries."""
        if not flags.FLAGS.cost_accounting:
            return None
        from . import trace as _trace
        full_key = (kind, ) + tuple(key)
        with self._COST_LOCK:
            reg = getattr(self, '_cost_entries', None)
            if reg is None:
                reg = self._cost_entries = {}
            if full_key in reg:
                return reg[full_key]
        entry = _trace.analyze_cost(jitted, args, kind=kind, steps=steps,
                                    fetch_names=self.fetch_names)
        with self._COST_LOCK:
            return reg.setdefault(full_key, entry)

    def cost_entries(self):
        """This executable set's captured cost-registry entries."""
        with self._COST_LOCK:
            return dict(getattr(self, '_cost_entries', None) or {})

    def run(self, scope, feed_values, rng_key, eager=False):
        state_rw, state_ro, feeds = self._materialize_args(
            scope, feed_values, cache_ro=True)
        if eager:
            new_state, fetches = self._run_eager(scope, state_rw, state_ro,
                                                 feeds, rng_key)
        else:
            self._capture_cost('run', (), self._jit,
                               (state_rw, state_ro, feeds, rng_key))
            new_state, fetches = self._jit(state_rw, state_ro, feeds, rng_key)
            if flags.FLAGS.check_nan_inf:
                _check_nan_inf(list(new_state.items()), 'state var')
                _check_nan_inf(zip(self.fetch_names, fetches), 'fetch')
        for name, val in new_state.items():
            scope.var(name).set_value(val)
        return fetches

    def run_multi(self, scope, feed_values, rng_key, steps,
                  scanned_feeds=None):
        """K steps in ONE device dispatch, per-iteration RNG via
        fold_in.  The dispatch-latency amortizer for small steps (a
        ~100ms tunnel round trip dwarfs a ~2ms LSTM step; reference
        benchmarks loop on the host because each CUDA launch is ~µs).

        feed_values: feeds held constant across iterations.
        scanned_feeds: {name: array with leading K axis} — one slice
        per iteration (a whole epoch shipped in one transfer), driven
        by lax.scan; without it the loop is a fori_loop over the same
        batch."""
        if steps < 1:
            raise ValueError('run_multi: steps must be >= 1, got %r'
                             % (steps, ))
        if any(_is_host_op(op) for op in self.ops):
            raise RuntimeError(
                'run_multi: the program contains host ops and cannot run '
                'as one on-device loop — use run() per step')
        state_rw, state_ro, feeds = self._materialize_args(
            scope, feed_values, cache_ro=True)
        scanned = scanned_feeds or {}
        jitted = self._get_multi_jit(feeds, scanned)
        self._capture_cost(
            'multi', (tuple(sorted(feeds)), tuple(sorted(scanned)),
                      int(steps)),
            jitted, (state_rw, state_ro, feeds, scanned, rng_key,
                     int(steps)),
            steps=steps)
        new_state, fetches = jitted(state_rw, state_ro, feeds,
                                    scanned, rng_key, int(steps))
        for name, val in new_state.items():
            scope.var(name).set_value(val)
        return fetches

    def _make_multi(self):
        """The K-steps-per-dispatch function: K-1 iterations inside
        lax.scan (per-step feeds) or fori_loop (constant feeds), last
        step unrolled so fetches come out.  Shared verbatim by the
        single-device and SPMD executors — only the jit wrapping
        (shardings) differs."""
        import jax
        fn = self._fn
        rw_keys = list(self.state_rw)

        def multi(state_rw, state_ro, feeds, scanned, rng, n):
            if scanned:
                def body(s, sl):
                    i, per_step = sl
                    merged = dict(feeds)
                    merged.update(per_step)
                    new_state, _ = fn(s, state_ro, merged,
                                      jax.random.fold_in(rng, i))
                    return ({k: new_state.get(k, s[k])
                             for k in rw_keys}, None)

                head = {k: v[:-1] for k, v in scanned.items()}
                final, _ = jax.lax.scan(
                    body, state_rw,
                    (jax.numpy.arange(n - 1), head))
                last = dict(feeds)
                last.update({k: v[-1] for k, v in scanned.items()})
            else:
                def body(i, s):
                    new_state, _ = fn(s, state_ro, feeds,
                                      jax.random.fold_in(rng, i))
                    return {k: new_state.get(k, s[k]) for k in rw_keys}

                final = jax.lax.fori_loop(0, n - 1, body, state_rw)
                last = feeds
            # last step outside the loop so fetches come out
            new_state, fetches = fn(final, state_ro, last,
                                    jax.random.fold_in(rng, n - 1))
            return new_state, fetches

        return multi

    def _wrap_multi_jit(self, feeds, scanned, donate):
        """jit wrapping for the train scan; _SpmdCompiledBlock overrides
        this to attach per-structure GSPMD shardings."""
        import jax
        return jax.jit(self._make_multi(), static_argnums=(5, ),
                       donate_argnums=donate)

    def _get_multi_jit(self, feeds, scanned):
        """One train-scan executable per (feeds, scanned) name structure.
        Like the eval scan, the scanned K-step feed block is DONATED on
        device: it is dead the moment the scan consumed it, so XLA
        recycles the buffer in place — the FeedPipeline's two in-flight
        dispatches then double-buffer the feed block instead of holding
        2x K batches of input alive."""
        key = (tuple(sorted(feeds)), tuple(sorted(scanned)))
        cache = getattr(self, '_multi_jits', None)
        if cache is None:
            cache = self._multi_jits = {}
        jitted = cache.get(key)
        if jitted is None:
            donate = (0, ) if self.state_rw else ()
            if scanned and self._device_platform() != 'cpu':
                # XLA CPU can't alias the scanned block (it would warn
                # and copy); on device the donation is the point
                donate = donate + (3, )
            jitted = self._wrap_multi_jit(feeds, scanned, donate)
            cache[key] = jitted
        return jitted

    def note_multi_compile(self, steps, scanned, seen_attr='_multi_steps_seen'):
        """True exactly when this (steps, scanned shape signature) pair
        has not run before — i.e. the coming dispatch is a real XLA
        retrace (`steps` is a static jit argument; each scanned
        structure/shape retraces too).  Shared compile_count
        bookkeeping for Executor.run_multi and
        ParallelExecutor.run_multi (and, via ``seen_attr``, their
        run_eval_multi counterparts — the eval scan is a different
        executable, so its retraces are tracked separately)."""
        seen = getattr(self, seen_attr, None)
        if seen is None:
            seen = set()
            setattr(self, seen_attr, seen)
        key = (int(steps),
               feed_signature(scanned) if scanned is not None else None)
        if key in seen:
            return False
        seen.add(key)
        return True

    def note_eval_compile(self, steps, scanned):
        """note_multi_compile for the EVAL scan's executable cache."""
        return self.note_multi_compile(steps, scanned,
                                       seen_attr='_eval_steps_seen')

    def _make_eval_multi(self):
        """The K-EVAL-batches-per-dispatch function: lax.scan over the
        lots, collecting EVERY iteration's fetches stacked on a leading
        K axis — inference serving wants all K results, unlike
        _make_multi's train loop which only surfaces the last step's.
        State still threads through the carry (an eval program normally
        writes none, but e.g. metric accumulators stay correct).
        Shared by the single-device and SPMD executors — only the jit
        wrapping (shardings) differs, exactly like _make_multi."""
        import jax
        import jax.numpy as jnp
        fn = self._fn
        rw_keys = list(self.state_rw)

        def eval_multi(state_rw, state_ro, feeds, scanned, rng, n):
            def body(s, sl):
                i, per_step = sl
                merged = dict(feeds)
                merged.update(per_step)
                new_state, fetches = fn(s, state_ro, merged,
                                        jax.random.fold_in(rng, i))
                return ({k: new_state.get(k, s[k])
                         for k in rw_keys}, fetches)

            final, stacked = jax.lax.scan(
                body, state_rw, (jnp.arange(n), scanned))
            return final, stacked

        return eval_multi

    def _wrap_eval_multi_jit(self, feeds, scanned, donate):
        """jit wrapping for the eval scan; _SpmdCompiledBlock overrides
        this to attach per-structure GSPMD shardings."""
        import jax
        return jax.jit(self._make_eval_multi(), static_argnums=(5, ),
                       donate_argnums=donate)

    def _get_eval_multi_jit(self, feeds, scanned):
        """One eval-scan executable per (feeds, scanned) name structure.
        The scanned K-lot input block is DONATED: it is dead the moment
        the scan consumed it, so XLA recycles the buffer in place — two
        pipelined serving dispatches then double-buffer the feed block
        instead of holding 2x K lots of input alive."""
        key = (tuple(sorted(feeds)), tuple(sorted(scanned)))
        cache = getattr(self, '_eval_jits', None)
        if cache is None:
            cache = self._eval_jits = {}
        jitted = cache.get(key)
        if jitted is None:
            donate = (0, ) if self.state_rw else ()
            if scanned and self._device_platform() != 'cpu':
                # XLA CPU can't alias the scanned block (it would warn
                # and copy); on device the donation is the point
                donate = donate + (3, )
            jitted = self._wrap_eval_multi_jit(feeds, scanned, donate)
            cache[key] = jitted
        return jitted

    def _device_platform(self):
        try:
            return self.place.jax_device().platform
        except Exception:
            return 'cpu'

    def run_eval_multi(self, scope, feed_values, rng_key, steps,
                       scanned_feeds=None):
        """K EVAL iterations in ONE device dispatch, returning every
        iteration's fetches stacked on a leading K axis (run_multi's
        inference analog — the remaining dispatch-tax ledger row).
        feed_values: feeds held constant across iterations (the bench's
        repeated-batch form); scanned_feeds: {name: [K, ...]} per-lot
        slices (the serving engine's form)."""
        if steps < 1:
            raise ValueError('run_eval_multi: steps must be >= 1, got %r'
                             % (steps, ))
        if any(_is_host_op(op) for op in self.ops):
            raise RuntimeError(
                'run_eval_multi: the program contains host ops and cannot '
                'run as one on-device loop — use run() per step')
        state_rw, state_ro, feeds = self._materialize_args(
            scope, feed_values, cache_ro=True)
        scanned = scanned_feeds or {}
        jitted = self._get_eval_multi_jit(feeds, scanned)
        # the serving engine reads last_eval_cost to derive achieved MFU
        # for the dispatch it is draining
        self.last_eval_cost = self._capture_cost(
            'eval_multi', (tuple(sorted(feeds)), tuple(sorted(scanned)),
                           int(steps)),
            jitted, (state_rw, state_ro, feeds, scanned, rng_key,
                     int(steps)),
            steps=steps)
        new_state, stacked = jitted(state_rw, state_ro, feeds, scanned,
                                    rng_key, int(steps))
        for name, val in new_state.items():
            scope.var(name).set_value(val)
        return stacked

    def note_decode_compile(self, steps, carry_sig):
        """note_multi_compile for the DECODE scan's executable cache."""
        return self.note_multi_compile(steps, carry_sig,
                                       seen_attr='_decode_steps_seen')

    def _make_decode_multi(self, spec):
        """The K-AUTOREGRESSIVE-steps-per-dispatch function (ISSUE 7):
        lax.scan over K greedy-decode steps of the step program, the
        whole slot batch at once.  Unlike _make_eval_multi, each step's
        INPUT comes from the previous step's OUTPUT — the scan carry
        holds the per-slot decoder state (KV/hidden — the ``state``
        pairs), the current token, a per-slot alive mask, and a
        per-slot remaining-step budget.  Stop conditions (EOS emitted /
        budget exhausted) are masked INSIDE the scan: a finished slot's
        state and token FREEZE (jnp.where on the alive mask), so dead
        and free slots ride along at zero semantic cost while live ones
        keep decoding — the in-jit half of continuous batching.
        Emits (carry', tokens [K, S], alive_in [K, S]): a token counts
        for a slot exactly when the slot was alive ENTERING the step
        (the EOS itself is emitted, then the slot goes dead) — the same
        accounting as a host-driven greedy loop that appends argmax
        until it appends end_id or exhausts max_len."""
        import jax
        import jax.numpy as jnp
        fn = self._fn
        rw_keys = list(self.state_rw)
        token_name = spec['token']
        end_id = int(spec['end_id'])
        updates = [(feed_n, self.fetch_names.index(fetch_n))
                   for feed_n, fetch_n in spec['state']]

        def decode_multi(state_ro, feeds, carry, rng, n):
            def body(c, i):
                s, slots, token = c['state'], c['slots'], c['token']
                alive, remaining = c['alive'], c['remaining']
                merged = dict(feeds)
                merged.update(slots)
                merged[token_name] = token
                new_state, fetches = fn(s, state_ro, merged,
                                        jax.random.fold_in(rng, i))
                logits = fetches[0]
                nxt = jnp.argmax(
                    logits.reshape((logits.shape[0], -1)),
                    axis=-1).astype(token.dtype)
                emit = jnp.where(alive, nxt,
                                 jnp.asarray(end_id, token.dtype))
                rem = remaining - alive.astype(remaining.dtype)
                live = alive & (emit != end_id) & (rem > 0)
                new_slots = dict(slots)
                for feed_n, fi in updates:
                    upd = fetches[fi]
                    keep = alive.reshape(
                        (-1, ) + (1, ) * (max(upd.ndim, 1) - 1))
                    new_slots[feed_n] = jnp.where(keep, upd,
                                                  slots[feed_n])
                new_token = jnp.where(alive[:, None], emit[:, None],
                                      token)
                c2 = {'state': {k: new_state.get(k, s[k])
                                for k in rw_keys},
                      'slots': new_slots, 'token': new_token,
                      'alive': live, 'remaining': rem}
                return c2, (emit, alive)

            final, (toks, alive_in) = jax.lax.scan(
                body, carry, jnp.arange(n))
            return final, toks, alive_in

        return decode_multi

    def _wrap_decode_multi_jit(self, feeds, carry, spec, donate):
        """jit wrapping for the decode scan; _SpmdCompiledBlock
        overrides this to attach per-structure GSPMD shardings (slots
        sharded batch-dim over dp, like eval lots)."""
        import jax
        return jax.jit(self._make_decode_multi(spec),
                       static_argnums=(4, ), donate_argnums=donate)

    def _get_decode_multi_jit(self, feeds, carry, spec):
        """One decode-scan executable per (constant-feed, slot, spec)
        name structure.  The CARRY is DONATED on device: the slot
        state (KV/hidden cache) is dead the moment the scan produced
        its successor, so XLA updates it IN PLACE — the resident
        decode cache never doubles during a dispatch."""
        key = (tuple(sorted(feeds)), tuple(sorted(carry['slots'])),
               spec['token'], spec['state'], spec['end_id'])
        cache = getattr(self, '_decode_jits', None)
        if cache is None:
            cache = self._decode_jits = {}
        jitted = cache.get(key)
        if jitted is None:
            donate = ()
            if self._device_platform() != 'cpu':
                # XLA CPU can't alias the carry (it would warn and
                # copy); on device the in-place state update is the
                # point
                donate = (2, )
            jitted = self._wrap_decode_multi_jit(feeds, carry, spec,
                                                 donate)
            cache[key] = jitted
        return jitted

    def run_decode_multi(self, scope, feed_values, rng_key, steps, carry,
                         spec):
        """K autoregressive decode steps in ONE device dispatch over
        the whole slot batch (run_eval_multi's generation sibling).
        ``carry`` is the engine-facing slot view (slots/token/alive/
        remaining); persistable RW state threads through the scan like
        every other path and persists back to the scope.  Returns
        (carry', tokens [K, S], alive_in [K, S]) with NO host sync —
        all three are async device values."""
        if steps < 1:
            raise ValueError('run_decode_multi: steps must be >= 1, '
                             'got %r' % (steps, ))
        if any(_is_host_op(op) for op in self.ops):
            raise RuntimeError(
                'run_decode_multi: the program contains host ops and '
                'cannot run as one on-device loop — decode-step '
                'programs must be pure compute')
        state_rw, state_ro, feeds = self._materialize_args(
            scope, feed_values, cache_ro=True)
        jitted = self._get_decode_multi_jit(feeds, carry, spec)
        full = {'state': state_rw, 'slots': dict(carry['slots']),
                'token': carry['token'], 'alive': carry['alive'],
                'remaining': carry['remaining']}
        self.last_decode_cost = self._capture_cost(
            'decode_multi',
            (tuple(sorted(feeds)), tuple(sorted(carry['slots'])),
             int(steps)),
            jitted, (state_ro, feeds, full, rng_key, int(steps)),
            steps=steps)
        final, toks, alive_in = jitted(state_ro, feeds, full, rng_key,
                                       int(steps))
        for name, val in final['state'].items():
            scope.var(name).set_value(val)
        carry_out = {'slots': final['slots'], 'token': final['token'],
                     'alive': final['alive'],
                     'remaining': final['remaining']}
        return carry_out, toks, alive_in

    def note_chunk_compile(self, width, carry_sig):
        """note_multi_compile for the CHUNK-prefill executable cache
        (the chunk width is the static shape knob, like steps for the
        scans)."""
        return self.note_multi_compile(width, carry_sig,
                                       seen_attr='_chunk_widths_seen')

    def _make_chunk_prefill(self, spec):
        """The C-tokens-per-dispatch PREFILL advance (ISSUE 14): run
        the chunk program over the WHOLE slot batch once — each
        PREFILLING slot consumes its next block of prompt tokens and
        its state slabs advance IN PLACE on the carry (the same donated
        carry the decode scans chain on, so chunk dispatches interleave
        with decode dispatches with no host round trip).  Slots not in
        the chunk (``aux['active']`` False: decoding, free, or already
        past their prompt) keep their slabs bitwise.  Slots whose
        prompt ENDS inside this block (``aux['finish']``) transition to
        decoding on the same dispatch: token <- start_id, alive <-
        True, remaining <- their step budget — the first decode scan
        dispatched after this chunk picks them up at a step boundary.
        Returns (carry', alive') where alive' is a separate small
        output the engine harvests to time the chunk (and surface a
        deferred device error) without touching the chained carry."""
        import jax.numpy as jnp
        fn = self._fn
        rw_keys = list(self.state_rw)
        start_id = int(spec['start_id'])
        updates = [(feed_n, self.fetch_names.index(fetch_n))
                   for feed_n, fetch_n in spec['state']]

        def chunk_prefill(state_ro, feeds, carry, aux, rng):
            s, slots = carry['state'], carry['slots']
            merged = dict(feeds)
            merged.update(slots)
            new_state, fetches = fn(s, state_ro, merged, rng)
            active = aux['active']
            new_slots = dict(slots)
            for feed_n, fi in updates:
                upd = fetches[fi]
                keep = active.reshape(
                    (-1, ) + (1, ) * (max(upd.ndim, 1) - 1))
                new_slots[feed_n] = jnp.where(keep, upd, slots[feed_n])
            fin = aux['finish']
            token = jnp.where(fin[:, None],
                              jnp.asarray(start_id, carry['token'].dtype),
                              carry['token'])
            alive = jnp.logical_or(carry['alive'], fin)
            remaining = jnp.where(fin, aux['budget'].astype(
                carry['remaining'].dtype), carry['remaining'])
            c2 = {'state': {k: new_state.get(k, s[k]) for k in rw_keys},
                  'slots': new_slots, 'token': token, 'alive': alive,
                  'remaining': remaining}
            return c2, alive

        return chunk_prefill

    def _wrap_chunk_prefill_jit(self, feeds, carry, spec, donate):
        """jit wrapping for the chunk-prefill advance; the SPMD block
        overrides this to shard every slot-leading leaf over dp, like
        the decode scan."""
        import jax
        return jax.jit(self._make_chunk_prefill(spec),
                       donate_argnums=donate)

    def _get_chunk_prefill_jit(self, feeds, carry, spec):
        """One chunk-prefill executable per (feed, slot, spec) name
        structure — the chunk width is part of the token feed's traced
        SHAPE, so a fixed ``prefill_chunk`` compiles exactly once (the
        ragged final block pads to the same width).  The carry is
        DONATED on device like the decode scan's."""
        key = (tuple(sorted(feeds)), tuple(sorted(carry['slots'])),
               spec['token'], spec['state'], spec['start_id'])
        cache = getattr(self, '_chunk_jits', None)
        if cache is None:
            cache = self._chunk_jits = {}
        jitted = cache.get(key)
        if jitted is None:
            donate = ()
            if self._device_platform() != 'cpu':
                donate = (2, )
            jitted = self._wrap_chunk_prefill_jit(feeds, carry, spec,
                                                  donate)
            cache[key] = jitted
        return jitted

    def run_chunk_prefill(self, scope, feed_values, rng_key, carry, aux,
                          spec):
        """ONE C-token prefill advance over the whole slot batch (the
        chunked-prefill sibling of run_decode_multi — ISSUE 14).
        ``feed_values`` carries the chunk program's block feeds (token
        block + optional per-slot lengths + the token feed's @SEQLEN
        companion); ``carry`` is the engine-facing slot view; ``aux``
        the per-slot active/finish/budget leaves.  Returns (carry',
        alive') with NO host sync."""
        if any(_is_host_op(op) for op in self.ops):
            raise RuntimeError(
                'run_chunk_prefill: the program contains host ops and '
                'cannot run as one on-device advance — chunk programs '
                'must be pure compute')
        state_rw, state_ro, feeds = self._materialize_args(
            scope, feed_values, cache_ro=True)
        jitted = self._get_chunk_prefill_jit(feeds, carry, spec)
        full = {'state': state_rw, 'slots': dict(carry['slots']),
                'token': carry['token'], 'alive': carry['alive'],
                'remaining': carry['remaining']}
        self.last_chunk_cost = self._capture_cost(
            'chunk_prefill',
            (tuple(sorted(feeds)), tuple(sorted(carry['slots']))),
            jitted, (state_ro, feeds, full, aux, rng_key))
        final, ok = jitted(state_ro, feeds, full, aux, rng_key)
        for name, val in final['state'].items():
            scope.var(name).set_value(val)
        carry_out = {'slots': final['slots'], 'token': final['token'],
                     'alive': final['alive'],
                     'remaining': final['remaining']}
        return carry_out, ok


class Executor(object):
    """Program runner (reference executor.py:256 / executor.cc:125)."""

    _CACHE_MAX = 64  # LRU bound; each entry pins its Program (stable ids)

    def __init__(self, place=None):
        import collections
        self.place = place if place is not None else core.CPUPlace()
        self._cache = collections.OrderedDict()
        self._rng = None
        self._closed = False
        # observability: compiles are the static-shape design's recompile
        # cost (vs the reference's LoD no-padding design) — each cache
        # miss below is one XLA compile; tests pin bounds on this
        self.compile_count = 0
        # the compile cache and RNG stream are shared mutable state: the
        # reference predictor's thread contract
        # (paddle_inference_api.h:90 — Clone() + concurrent Run()) means
        # N threads may resolve through ONE executor concurrently, and
        # an unguarded OrderedDict get/move_to_end/popitem interleaving
        # corrupts the LRU (or drops a live entry mid-resolve)
        self._cache_lock = threading.RLock()

    def _next_rng(self, program):
        # Keys are built HOST-side as raw uint32[2] threefry keys — a
        # device-side jax.random.split would dispatch a separate tiny
        # computation every step, serializing ~12ms of runtime round trip
        # against the training step.  A numpy key rides the jit call's own
        # argument transfer instead.
        if flags.FLAGS.cpu_deterministic or flags.FLAGS.cudnn_deterministic:
            # deterministic mode (reference FLAGS_cpu_deterministic,
            # build_strategy.h:41): key depends only on (program seed,
            # per-program step index), so streams are independent of what
            # else this Executor has run.  Weakref keys make entries die
            # with their program — no unbounded growth, no recycled-id
            # aliasing
            import weakref
            with self._cache_lock:
                if not hasattr(self, '_det_steps'):
                    self._det_steps = {}
                key = weakref.ref(program,
                                  lambda r: self._det_steps.pop(r, None))
                step = self._det_steps.get(key, 0)
                self._det_steps[key] = step + 1
            return np.array([(program.random_seed or 0) & 0xffffffff, step],
                            np.uint32)
        with self._cache_lock:
            # concurrent predictors (Clone + threaded Run) share this
            # stream: the counter bump must be atomic or two threads
            # can mint one key twice
            if self._rng is None:
                # mask to the key word width: PRNGKey accepted 64-bit
                # and negative seeds, so keep accepting them
                self._rng_seed = int(program.random_seed or 0) & 0xffffffff
                self._rng = 0
            self._rng += 1
            return np.array([self._rng_seed, self._rng], np.uint32)

    def as_lodtensor(self, data):
        return core.LoDTensor(np.asarray(data))

    def _pin_cache_lifetime(self, obj):
        """Purge this executor's cache entries keyed by id(obj) when obj is
        garbage-collected, so recycled ids can't alias stale compiles."""
        import weakref
        attr = '_ptpu_cache_final_%d' % id(self)
        if getattr(obj, attr, None) is not None:
            return
        cache_ref = weakref.ref(self._cache)
        self_ref = weakref.ref(self)
        oid = id(obj)

        def _purge(cache_ref=cache_ref, self_ref=self_ref, oid=oid):
            cache = cache_ref()
            if cache is not None:
                # GC can fire this on any thread: exclude a concurrent
                # _resolve_and_compile mid-LRU-update (the executor —
                # and with it the lock — outlives its cache entries)
                owner = self_ref()
                lock = owner._cache_lock if owner is not None else None
                import contextlib
                with lock if lock is not None \
                        else contextlib.nullcontext():
                    for k in [k for k in list(cache)
                              if oid in (k[0], k[5])]:
                        cache.pop(k, None)

        try:
            setattr(obj, attr, weakref.finalize(obj, _purge))
        except AttributeError:
            pass  # object without a __dict__; fall back to LRU semantics

    def _resolve_and_compile(self, program, feed, fetch_list, scope,
                             pop_readers=True):
        """Shared front half of run()/memory_analysis(): normalize the
        arguments, prepare/validate feeds, and resolve (or build) the
        cached executable.  ``pop_readers=False`` for analysis paths
        that never execute the program — consuming a py_reader batch
        there would silently drop a minibatch from training."""
        if self._closed:
            raise RuntimeError('Attempted to use a closed Executor')
        program = program if program is not None else \
            default_main_program()
        scope = scope if scope is not None else _current_scope()
        feed = dict(feed if feed is not None else {})
        fetch_list = fetch_list if fetch_list is not None else []
        if isinstance(fetch_list, (Variable, str)):
            fetch_list = [fetch_list]
        fetch_names = [
            f.name if isinstance(f, Variable) else str(f)
            for f in fetch_list
        ]
        from .layers import io as layers_io
        layers_io.note_executor_place(self.place)
        if pop_readers:
            _pop_readers_into_feed(program, feed, self.place)
        feed_arrays = prepare_feed_arrays(feed)
        validate_feed(program, feed_arrays)
        sig = feed_signature(feed_arrays)
        key = (id(program), program._version, tuple(fetch_names), sig,
               self.place, id(scope), registry.amp_enabled())
        # id()-keyed entries are purged when the keyed object dies, so a
        # recycled id can never alias a stale compile (the LRU alone
        # can't guarantee this: evicting one entry may unpin a program
        # whose id recurs while sibling entries survive)
        self._pin_cache_lifetime(program)
        self._pin_cache_lifetime(scope)
        with self._cache_lock:
            compiled = self._cache.get(key)
            if compiled is None:
                self.compile_count += 1
                compiled = _CompiledBlock(program, 0,
                                          [n for n, _, _ in sig],
                                          fetch_names, self.place, scope)
                self._cache[key] = compiled
                if len(self._cache) > self._CACHE_MAX:
                    self._cache.popitem(last=False)
            else:
                self._cache.move_to_end(key)
        return program, scope, feed_arrays, compiled

    def memory_analysis(self, program=None, feed=None, fetch_list=None,
                        scope=None):
        """XLA buffer-assignment stats for the compiled program (the
        measured counterpart of the reference memory_optimize's print
        log): returns the jax CompiledMemoryStats — in particular
        ``temp_size_in_bytes``, the peak intermediate-buffer footprint
        after XLA's liveness-driven reuse.  Feeds must be shaped like a
        real run's (they key the compile)."""
        import jax
        program = program if program is not None else \
            default_main_program()
        if any(op.type == 'read' for op in program.block(0).ops):
            raise RuntimeError(
                'memory_analysis: the program is reader-fed; popping a '
                'py_reader batch here would silently drop a minibatch '
                'from training — pass representative arrays via feed= '
                'on a reader-free clone instead')
        program, scope, feed_arrays, compiled = self._resolve_and_compile(
            program, feed, fetch_list, scope, pop_readers=False)
        if any(_is_host_op(op) for op in compiled.ops):
            raise RuntimeError(
                'memory_analysis: the program contains host ops '
                '(%s) and runs on the eager path, which has no single '
                'compiled executable — remove them or analyse the '
                'compute-only portion' % sorted(
                    {op.type for op in compiled.ops
                     if _is_host_op(op)}))
        state_rw, state_ro, feeds = compiled._materialize_args(
            scope, feed_arrays)
        rng = jax.random.PRNGKey(0)
        return compiled._jit.lower(
            state_rw, state_ro, feeds, rng).compile().memory_analysis()

    def run(self,
            program=None,
            feed=None,
            fetch_list=None,
            feed_var_name='feed',
            fetch_var_name='fetch',
            scope=None,
            return_numpy=True,
            use_program_cache=False):
        program, scope, feed_arrays, compiled = self._resolve_and_compile(
            program, feed, fetch_list, scope)

        eager = any(_is_host_op(op) for op in compiled.ops)
        rng = self._next_rng(program)
        from . import profiler as _profiler
        if _profiler.is_profiler_enabled() and not flags.FLAGS.benchmark:
            # one timeline slice per run (the reference profiler records
            # per-op RecordEvents; whole-block XLA execution makes the
            # run the natural host-side unit — device-side op slices
            # come from the xplane capture).  The slice must cover
            # device time, not just the async dispatch, so sync inside.
            with _profiler.record_block(
                    'executor_run/block0[%s]' %
                    (compiled.fetch_names and
                     ','.join(compiled.fetch_names) or 'nofetch')):
                fetches = compiled.run(scope, feed_arrays, rng,
                                       eager=eager)
                for f in fetches:
                    if hasattr(f, 'block_until_ready'):
                        f.block_until_ready()
            return self._convert_fetches(fetches, return_numpy)
        if flags.FLAGS.benchmark:
            import time as _time
            t0 = _time.perf_counter()
            fetches = compiled.run(scope, feed_arrays, rng, eager=eager)
            for f in fetches:  # sync without disturbing fetch types
                if hasattr(f, 'block_until_ready'):
                    f.block_until_ready()
            import logging
            logging.getLogger('paddle_tpu').info(
                'FLAGS_benchmark: run %.3f ms, %d fetches',
                (_time.perf_counter() - t0) * 1e3, len(fetches))
        else:
            fetches = compiled.run(scope, feed_arrays, rng, eager=eager)
        return self._convert_fetches(fetches, return_numpy)

    def run_multi(self,
                  program=None,
                  feed=None,
                  fetch_list=None,
                  steps=1,
                  scope=None,
                  return_numpy=True,
                  feed_list=None,
                  reader=None,
                  embed_caches=None):
        """Run ``steps`` iterations of the program as ONE device
        dispatch.  Returns the LAST iteration's fetches.  For
        dispatch-bound small steps — e.g. the stacked-LSTM benchmark
        where a ~2ms step rides a ~100ms tunnel round trip — this makes
        the wall clock measure the chip.  Training state updates
        persist to the scope exactly as ``steps`` sequential run()
        calls would.

        feed: one batch reused every iteration (fori_loop), OR
        feed_list: a list of per-iteration batches (same shapes/LoD
        bucket) scanned on device — a mini-epoch in one dispatch;
        ``steps`` is then len(feed_list), OR
        reader: the program's py_reader — ``steps`` DISTINCT fresh
        minibatches drain from its queue and scan as one dispatch
        (the reference per-iteration pull, executor.cc:321-339); a
        stream ending mid-block trains on the shorter tail, an
        exhausted reader raises core.EOFException exactly like run().
        Overlapped staging across dispatches is fluid.FeedPipeline.

        embed_caches: two-tier embedding stores (ISSUE 12,
        ``distributed.CachedEmbeddingTable``) whose tables this program
        looks up: each cache's id feeds REMAP to slab slots on host,
        and the block's row exchange (dirty evictions out to the host
        master, fetched misses in) applies right before the dispatch.
        Synchronous form — the overlapped prefetch is
        FeedPipeline(embed_caches=)."""
        if reader is not None:
            from .dataflow import check_reader_args, drain_reader_feed_list
            check_reader_args('run_multi', feed, feed_list)
            program = program if program is not None else \
                default_main_program()
            feed_list = drain_reader_feed_list(program, reader, steps,
                                               self.place)
        else:
            # the guard covers BOTH plain-feed paths: they would
            # otherwise pop ONE reader minibatch in _resolve_and_compile
            # and silently train K steps on it
            program = _reject_reader_fed(program, 'run_multi')
        exchanges = []
        if embed_caches:
            # the scope check must precede ANY staging: a mis-bound
            # cache must not have its directory/metrics mutated by a
            # block that will never dispatch
            run_scope = scope if scope is not None else _current_scope()
            for cache in embed_caches:
                cache.check_scope(run_scope, 'run_multi')
        if feed_list is not None:
            if feed is not None:
                raise ValueError('run_multi: pass feed OR feed_list')
            steps, per_step = prepare_feed_list(feed_list)
            for cache in (embed_caches or ()):
                # remap the cache's id feeds to slab slots IN PLACE
                # (before per_step[0] keys the compile signature)
                exchanges.append(
                    (cache, cache.stage_feed_list(per_step, steps=steps)))
            feed = per_step[0]  # keys the compile signature (already
            # prepared: prepare_feed_arrays passes arrays through, so
            # the resolve path does not re-pad batch 0)
        elif embed_caches:
            # the constant-batch (fori_loop) form: one id set reused
            # every iteration — remap it once
            feed = prepare_feed_arrays(dict(feed if feed is not None
                                            else {}))
            for cache in embed_caches:
                exchanges.append(
                    (cache, cache.stage_feed_list([feed], steps=steps)))
        program, scope, feed_arrays, compiled = self._resolve_and_compile(
            program, feed, fetch_list, scope, pop_readers=False)
        scanned = None
        if feed_list is not None:
            import jax
            dev = self.place.jax_device()
            scanned = {
                n: jax.device_put(
                    stack_steps([fa[n] for fa in per_step]), dev)
                for n in per_step[0]
            }
            feed_arrays = {}  # every feed name arrives via the scan
        rng = self._next_rng(program)
        # each distinct `steps` value is its own XLA compile (static
        # arg), and so is each scanned-feed SHAPE signature (the jit
        # retraces per pytree structure) — the seen-set keys on the
        # full _multi_jit cache key so recompile-bound tests observe
        # real XLA retraces, not just distinct step counts
        if compiled.note_multi_compile(steps, scanned):
            self.compile_count += 1
        for cache, ex in exchanges:
            # the block's row exchange lands right before its dispatch
            # (an unfinished host fetch is a counted prefetch_stall)
            cache.apply(ex)
        from . import profiler as _profiler
        if _profiler.is_profiler_enabled():
            with _profiler.record_block(
                    'executor_run_multi/block0[x%d]' % int(steps)):
                fetches = compiled.run_multi(scope, feed_arrays, rng,
                                             steps, scanned_feeds=scanned)
                for f in fetches:
                    if hasattr(f, 'block_until_ready'):
                        f.block_until_ready()
            return self._convert_fetches(fetches, return_numpy)
        fetches = compiled.run_multi(scope, feed_arrays, rng, steps,
                                     scanned_feeds=scanned)
        return self._convert_fetches(fetches, return_numpy)

    def _dispatch_multi_scanned(self, program, fetch_list, scope,
                                sig_feed, scanned, steps):
        """Async front half of a scanned run_multi dispatch (the
        FeedPipeline drives this): resolve + compile keyed on
        ``sig_feed`` (the first prepared per-step feed dict), dispatch
        ONE pre-staged [K, ...] scanned block, and return the raw
        device fetches with NO host sync — so the host can stage block
        N+1 (and deliver block N-1) while N still computes.  State
        write-back to the scope happens inside (async device arrays)."""
        program, scope, _, compiled = self._resolve_and_compile(
            program, sig_feed, fetch_list, scope, pop_readers=False)
        rng = self._next_rng(program)
        if compiled.note_multi_compile(steps, scanned):
            self.compile_count += 1
        from . import trace as _trace
        _trace.flight_recorder.record(
            'multi_dispatch', executor='Executor', steps=int(steps),
            fetch_names=list(compiled.fetch_names),
            trace_id=getattr(_trace.current(), 'trace_id', None))
        fetches = compiled.run_multi(scope, {}, rng, int(steps),
                                     scanned_feeds=scanned)
        return fetches, compiled

    def _dispatch_eval_multi(self,
                             program=None,
                             feed=None,
                             fetch_list=None,
                             steps=None,
                             scope=None,
                             feed_list=None,
                             reader=None):
        """Async front half of run_eval_multi: resolve + compile, pad
        ragged lots to one shape bucket, dispatch ONE scanned eval, and
        return ``(stacked_fetches, reals, target, compiled, k)`` with NO
        host sync — the serving engine drives this directly so the host
        can feed dispatch N+1 (and trim/deliver N-1) while N still
        computes on device.  ``reals`` is the per-step real row count
        (None when nothing was padded), ``target`` the padded rows.
        ``reader=`` drains up to ``steps`` DISTINCT eval minibatches
        from the program's py_reader queue onto the feed_list path (the
        eval twin of run_multi's reader mode, same drain contract:
        bucket-boundary split pushes the ragged tail back, EOF raises)."""
        if reader is not None:
            from .dataflow import check_reader_args, drain_reader_feed_list
            check_reader_args('run_eval_multi', feed, feed_list, steps,
                              require_steps=True)
            program = program if program is not None else \
                default_main_program()
            feed_list = drain_reader_feed_list(program, reader, steps,
                                               self.place)
        else:
            program = _reject_reader_fed(program, 'run_eval_multi')
        reals, target, batch_feed_names, per_step = None, None, None, None
        if feed_list is not None:
            if feed is not None:
                raise ValueError('run_eval_multi: pass feed OR feed_list')
            if not feed_list:
                raise ValueError('run_eval_multi: feed_list is empty')
            per_step = [prepare_feed_arrays(dict(f)) for f in feed_list]
            check_feed_list_names(per_step, 'run_eval_multi')
            normalize_trailing_feed_list(per_step)
            from .parallel_executor import pad_ragged_batch, \
                normalize_ragged_feed_list
            per_step, reals, target, batch_feed_names = \
                normalize_ragged_feed_list(
                    per_step, lambda fa, **kw: pad_ragged_batch(fa, 1, **kw))
            steps = len(per_step)
            check_feed_list_uniform(per_step)
            feed = per_step[0]
        elif steps is None:
            raise ValueError('run_eval_multi: pass steps= with feed=')
        steps = int(steps)
        # pop_readers=False: the reader path already drained its batches
        # above (popping again here would silently eat a minibatch), and
        # every other path rejects reader-fed programs outright
        program, scope, feed_arrays, compiled = self._resolve_and_compile(
            program, feed, fetch_list, scope, pop_readers=False)
        if batch_feed_names is not None and \
                getattr(compiled, '_batch_feed_names', None) is None:
            # deterministic in the feed signature (which keys the cache
            # entry), so setting it once at first resolve is consistent
            # for every later hit — same contract as ParallelExecutor
            compiled._batch_feed_names = frozenset(batch_feed_names)
        scanned = None
        if per_step is not None:
            import jax
            dev = self.place.jax_device()
            scanned = {
                n: jax.device_put(
                    stack_steps([fa[n] for fa in per_step]), dev)
                for n in per_step[0]
            }
            feed_arrays = {}  # every feed name arrives via the scan
        rng = self._next_rng(program)
        if compiled.note_eval_compile(steps, scanned):
            self.compile_count += 1
        from . import trace as _trace
        _trace.flight_recorder.record(
            'eval_dispatch', executor='Executor', steps=int(steps),
            fetch_names=list(compiled.fetch_names),
            trace_id=getattr(_trace.current(), 'trace_id', None))
        stacked = compiled.run_eval_multi(scope, feed_arrays, rng, steps,
                                          scanned_feeds=scanned)
        return stacked, reals, target, compiled, steps

    def run_eval_multi(self,
                       program=None,
                       feed=None,
                       fetch_list=None,
                       steps=None,
                       scope=None,
                       return_numpy=True,
                       feed_list=None,
                       reader=None):
        """Run ``steps`` EVAL iterations of the program as ONE device
        dispatch and return EVERY iteration's fetches — the inference
        analog of run_multi (which surfaces only the last step), closing
        the dispatch-tax ledger's last row.  Returns one entry per
        fetch: a [K, ...]-stacked array, except batch-led fetches over
        ragged lots of UNEQUAL real row counts, which come back as a
        list of K per-step arrays trimmed to each lot's real rows.

        feed: one batch evaluated ``steps`` times (the bench's
        device-true timing form), OR feed_list: per-iteration lots
        scanned on device (the serving engine's form; ``steps`` is then
        len(feed_list)), OR reader: the program's py_reader — up to
        ``steps`` DISTINCT fresh eval minibatches drain from its queue
        and scan as one dispatch (the eval sweep's symmetric mode to
        run_multi's reader=; a stream ending mid-block evaluates the
        shorter tail, a shape-bucket boundary splits the block with the
        tail pushed back, an exhausted reader raises core.EOFException
        exactly like run()).  Ragged lots are padded to one shape
        bucket with masked replicated rows and trimmed on the way out."""
        from . import profiler as _profiler

        def go():
            stacked, reals, target, compiled, k = self._dispatch_eval_multi(
                program, feed=feed, fetch_list=fetch_list, steps=steps,
                scope=scope, feed_list=feed_list, reader=reader)
            return convert_eval_fetches(stacked, reals, target, compiled,
                                        k, return_numpy)

        if _profiler.is_profiler_enabled():
            with _profiler.record_block(
                    'executor_run_eval_multi/block0'):
                return go()  # np.asarray in the conversion drains
        return go()

    def run_decode_multi(self, program=None, feed=None, carry=None,
                         steps=None, decode=None, scope=None):
        """Run ``steps`` AUTOREGRESSIVE greedy-decode iterations of a
        STEP program as ONE device dispatch over a whole slot batch
        (ISSUE 7 — the generation sibling of run_eval_multi, and the
        serving engine's decode-lane primitive).  Each iteration feeds
        the previous iteration's outputs back in: ``decode`` names the
        token feed, the logits fetch (argmax = next token), the
        (state feed, state fetch) pairs threading KV/hidden state
        through the scan carry, optional read-only ``context`` slot
        feeds, and ``end_id``; per-slot stop conditions (EOS emitted /
        ``carry['remaining']`` exhausted) are masked INSIDE the scan —
        finished slots freeze, live ones keep decoding.

        carry: {'slots': {name: [S, ...]}, 'token': [S, 1] int,
        'alive': [S] bool, 'remaining': [S] int32} — the slot-resident
        decode state (on device it is DONATED and updated in place).
        feed: feeds held constant across iterations (rarely needed).
        Returns (carry', tokens [K, S], alive_in [K, S]): tokens[i, s]
        counts for slot s exactly when alive_in[i, s] — token-identical
        to a per-slot host-driven greedy loop over the same program."""
        carry_out, toks, alive_in, _ = self._dispatch_decode_multi(
            program, feed=feed, carry=carry, steps=steps, decode=decode,
            scope=scope)
        return carry_out, toks, alive_in

    def _dispatch_decode_multi(self, program=None, feed=None, carry=None,
                               steps=None, decode=None, scope=None):
        """Async front half of run_decode_multi (ISSUE 9 — the engine's
        PIPELINED decode lane drives this, the decode twin of
        _dispatch_multi_scanned): resolve + compile the K-step decode
        scan and dispatch it against a carry whose leaves may be
        DEVICE-RESIDENT — in particular the untouched (donated) output
        carry of the PREVIOUS decode dispatch, so scan N+1 chains
        straight onto scan N with no token block ever materializing on
        host between them.  Returns (carry', tokens [K, S], alive_in
        [K, S], compiled) with NO host sync: all three values are async
        device arrays the caller harvests when it chooses (the chained
        lane harvests scan N's tokens while N+1 computes).  Device
        leaves pass through signature/canonicalization untouched
        (prepare_feed_arrays / canonical_decode_carry are identity on
        jax.Arrays), so a chained dispatch costs the host only the
        cache lookup."""
        program = _reject_reader_fed(program, 'run_decode_multi')
        if carry is None or steps is None or decode is None:
            raise ValueError('run_decode_multi: carry=, steps= and '
                             'decode= are required')
        steps = int(steps)
        spec = normalize_decode_spec(decode)
        check_decode_carry(carry, spec, 'run_decode_multi')
        carry = canonical_decode_carry(carry)
        fetch_list = [spec['logits']] + [f for _, f in spec['state']]
        sig_feed = dict(feed or {})
        sig_feed[spec['token']] = carry['token']
        sig_feed.update(carry['slots'])
        program, scope, feed_arrays, compiled = self._resolve_and_compile(
            program, sig_feed, fetch_list, scope, pop_readers=False)
        const = {n: v for n, v in feed_arrays.items()
                 if n not in carry['slots'] and n != spec['token']}
        rng = self._next_rng(program)
        carry_sig = dict(carry['slots'])
        carry_sig[spec['token']] = carry['token']
        if compiled.note_decode_compile(steps, carry_sig):
            self.compile_count += 1
        from . import trace as _trace
        _trace.flight_recorder.record(
            'decode_dispatch', executor='Executor', steps=steps,
            slots=int(np.shape(carry['token'])[0]),
            trace_id=getattr(_trace.current(), 'trace_id', None))
        carry_out, toks, alive_in = compiled.run_decode_multi(
            scope, const, rng, steps, carry, spec)
        return carry_out, toks, alive_in, compiled

    def _dispatch_chunk_prefill(self, program=None, feed=None, carry=None,
                                aux=None, chunk=None, scope=None):
        """Async front half of chunked prefill (ISSUE 14 — the engine's
        chunk lane drives this, the chunk twin of
        _dispatch_decode_multi): resolve + compile the C-token prefill
        advance of a CHUNK program and dispatch it against a carry
        whose leaves may be DEVICE-RESIDENT (the chained decode
        carry), returning (carry', alive', compiled) with NO host
        sync.  ``feed`` carries the [S, C, 1] token block, its @SEQLEN
        companion, and the optional per-slot length feed; ``aux`` the
        active/finish/budget slot masks."""
        program = _reject_reader_fed(program, 'run_chunk_prefill')
        if carry is None or aux is None or chunk is None:
            raise ValueError('run_chunk_prefill: carry=, aux= and '
                             'chunk= are required')
        spec = normalize_chunk_spec(chunk)
        carry = canonical_decode_carry(carry)
        check_chunk_aux(aux, 'run_chunk_prefill',
                        slots=int(np.shape(carry['token'])[0]))
        fetch_list = [f for _, f in spec['state']]
        sig_feed = dict(feed or {})
        sig_feed.update(carry['slots'])
        program, scope, feed_arrays, compiled = self._resolve_and_compile(
            program, sig_feed, fetch_list, scope, pop_readers=False)
        block_feed = {n: v for n, v in feed_arrays.items()
                      if n not in carry['slots']}
        rng = self._next_rng(program)
        width = int(np.shape(feed_arrays[spec['token']])[1])
        carry_sig = dict(carry['slots'])
        carry_sig[spec['token']] = feed_arrays[spec['token']]
        if compiled.note_chunk_compile(width, carry_sig):
            self.compile_count += 1
        from . import trace as _trace
        _trace.flight_recorder.record(
            'chunk_dispatch', executor='Executor', width=width,
            slots=int(np.shape(carry['token'])[0]),
            trace_id=getattr(_trace.current(), 'trace_id', None))
        carry_out, ok = compiled.run_chunk_prefill(
            scope, block_feed, rng, carry, aux, spec)
        return carry_out, ok, compiled

    def _convert_fetches(self, fetches, return_numpy):
        def convert(f):
            from ..ops.sparse import SparseRows
            if isinstance(f, core.SelectedRows):
                return f
            if isinstance(f, SparseRows):
                sr = core.SelectedRows(
                    rows=np.asarray(f.rows).tolist(), height=f.height)
                sr.get_tensor().set(np.asarray(f.values))
                return sr
            return np.asarray(f) if return_numpy else core.LoDTensor(
                np.asarray(f))

        return [convert(f) for f in fetches]

    def cost_report(self):
        """Per-executable cost registry (ISSUE 6): every cached
        executable's XLA cost/memory analysis captured under
        FLAGS_cost_accounting — the ground truth behind achieved-MFU
        serving metrics and bench.py's cost-derived MFU."""
        with self._cache_lock:
            blocks = list(self._cache.values())
        return collect_cost_report(blocks)

    def close(self):
        """Reference Executor.Close() notifies pservers (executor.h:51); here
        it just drops the compile cache."""
        self._cache = {}
        self._closed = True
