"""DataFeeder: convert python/numpy minibatch rows into feed tensors
(reference: python/paddle/fluid/data_feeder.py:83)."""

import numpy as np

from . import core
from .framework import Variable, default_main_program

__all__ = ['DataFeeder']


class DataToLoDTensorConverter(object):
    """Accumulates per-example data, emits one (possibly LoD) tensor
    (reference data_feeder.py:29)."""

    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = shape
        self.dtype = core.convert_dtype_to_np(dtype)
        self._reset()

    def _reset(self):
        self.data = []
        self.lod = [[] for _ in range(self.lod_level)]

    def feed(self, data):
        self._feed_impl_(data, self.lod, self.lod_level)

    def _feed_impl_(self, data, lod, lod_level):
        if lod_level == 0:
            self.data.append(data)
        else:
            lod[0].append(len(data))
            for each_data in data:
                self._feed_impl_(each_data, lod[1:], lod_level - 1)

    def done(self):
        if self.lod_level == 0:
            arr = np.array(self.data, dtype=self.dtype)
            if self.shape:
                try:
                    arr = arr.reshape((-1, ) + tuple(
                        s for s in self.shape[1:] if s > 0)) \
                        if -1 in self.shape or arr.size else arr
                except ValueError:
                    pass
            t = core.LoDTensor(arr)
        else:
            flat = []

            def _flatten(d, level):
                if level == 0:
                    flat.append(d)
                else:
                    for x in d:
                        _flatten(x, level - 1)

            for row in self.data:
                _flatten(row, 0)
            arr = np.concatenate(
                [np.asarray(d, dtype=self.dtype).reshape(
                    (-1, ) + tuple(s for s in self.shape[1:] if s > 0))
                 for d in self.data]) if self.data else np.empty(
                     (0, ), dtype=self.dtype)
            t = core.LoDTensor(arr)
            t.set_recursive_sequence_lengths(self.lod)
        self._reset()
        return t


class DataFeeder(object):
    def __init__(self, feed_list, place, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError('Feed list should contain Variables')
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
        self.place = place

    def feed(self, iterable):
        converters = [
            DataToLoDTensorConverter(
                place=self.place,
                lod_level=lod_level,
                shape=shape,
                dtype=dtype)
            for lod_level, shape, dtype in zip(
                self.feed_lod_level, self.feed_shapes, self.feed_dtypes)
        ]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                'The number of fields in data (%s) does not match len(feed_list)'
                ' (%s)' % (len(each_sample), len(converters)))
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        ret_dict = {}
        for each_name, each_converter in zip(self.feed_names, converters):
            ret_dict[each_name] = each_converter.done()
        return ret_dict

    def decorate_reader(self, reader, multi_devices=False, num_places=None,
                        drop_last=True):
        """Wrap a batched sample reader into one yielding ready feed
        dicts (reference data_feeder.py decorate_reader)."""

        def decorated():
            n_places = num_places or 1
            for batch in reader():
                if multi_devices:
                    batch = list(batch)
                    rem = len(batch) % n_places
                    if rem and drop_last:
                        # uneven final shard sizes would give devices
                        # mismatched shapes — drop the remainder
                        batch = batch[:len(batch) - rem]
                    if len(batch) < n_places:
                        continue  # cannot cover every device
                    yield self.feed_parallel(batch, n_places)
                else:
                    yield self.feed(batch)

        return decorated

    def feed_parallel(self, iterable, num_places=None):
        """Split a batch across devices (reference data_feeder.py:201) —
        kept for API parity; SPMD sharding supersedes it."""
        if num_places is None:
            num_places = 1
        batches = [[] for _ in range(num_places)]
        for i, sample in enumerate(iterable):
            batches[i % num_places].append(sample)
        return [self.feed(b) for b in batches if b]
