"""CSP concurrency: Go routines, channels, Select
(reference: python/paddle/fluid/concurrency.py; C++ side
framework/channel.h + operators/concurrency/channel_*_op.cc, go_op.cc,
select_op.cc).

The channel runtime is native (csrc/channel.cc via runtime.native
.NativeChannel): bounded buffered channels and capacity-0 rendezvous,
blocking + try variants.  Programs using these ops execute on the host
eager path (they are inherently sequential control constructs); compute
inside Go blocks still lowers per-op to XLA.
"""

import contextlib
import io
import threading

import numpy as np

from .framework import default_main_program
from .layer_helper import LayerHelper
from . import core

__all__ = [
    'Go', 'make_channel', 'channel_send', 'channel_recv', 'channel_close',
    'Select'
]


def _serialize(arr):
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def _deserialize(data):
    return np.load(io.BytesIO(bytes(data)), allow_pickle=False)


class Go(object):
    """``with fluid.Go():`` runs the enclosed ops on their own thread
    (reference concurrency.py:28 Go(BlockGuard) emitting go_op)."""

    def __init__(self, name=None):
        self.helper = LayerHelper('go', name=name)

    def __enter__(self):
        self.main_program = self.helper.main_program
        self.parent_idx = self.main_program.current_block_idx
        self.sub_block = self.main_program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.main_program.rollback()
        parent_block = self.main_program.block(self.parent_idx)
        parent_block.append_op(
            type='go', inputs={}, outputs={},
            attrs={'sub_block': self.sub_block})
        return True


def make_channel(dtype, capacity=0):
    """Create a channel variable (reference concurrency.py:282;
    channel_create_op).  dtype is accepted for API parity; payloads carry
    their own dtype."""
    helper = LayerHelper('channel_create')
    ch = helper.create_variable_for_type_inference(dtype='float32')
    ch.stop_gradient = True
    helper.append_op(
        type='channel_create',
        outputs={'Out': [ch]},
        attrs={'capacity': capacity,
               'data_type': str(dtype)})
    return ch


def channel_send(channel, value, is_copy=False):
    """Blocking send (reference concurrency.py:338; channel_send_op).
    Returns a bool status variable."""
    helper = LayerHelper('channel_send')
    status = helper.create_variable_for_type_inference(dtype='bool')
    status.stop_gradient = True
    helper.append_op(
        type='channel_send',
        inputs={'Channel': [channel],
                'X': [value]},
        outputs={'Status': [status]})
    return status


def channel_recv(channel, return_value):
    """Blocking receive into return_value (reference concurrency.py:388;
    channel_recv_op).  Returns (return_value, status)."""
    helper = LayerHelper('channel_recv')
    status = helper.create_variable_for_type_inference(dtype='bool')
    status.stop_gradient = True
    helper.append_op(
        type='channel_recv',
        inputs={'Channel': [channel]},
        outputs={'Out': [return_value],
                 'Status': [status]})
    return return_value, status


def channel_close(channel):
    """(reference concurrency.py:432; channel_close_op)"""
    helper = LayerHelper('channel_close')
    helper.append_op(type='channel_close', inputs={'Channel': [channel]})


class Select(object):
    """Go-style select over channel operations (reference
    concurrency.py:196; select_op).  Cases are tried in order; the first
    ready channel op runs its block; ``default()`` runs when none is
    ready (without it, select blocks until one becomes ready)."""

    def __init__(self, name=None):
        self.helper = LayerHelper('select', name=name)
        self.cases = []  # (kind, channel_name, value_name, sub_block)
        self.has_default = False

    def __enter__(self):
        self.main_program = self.helper.main_program
        self.parent_idx = self.main_program.current_block_idx
        return self

    @contextlib.contextmanager
    def case(self, channel_action_fn, channel, value, is_copy=False):
        kind = ('send' if channel_action_fn is channel_send else 'recv')
        sub_block = self.main_program.create_block()
        try:
            yield
        finally:
            self.main_program.rollback()
        self.cases.append((kind, channel.name, value.name, sub_block))

    @contextlib.contextmanager
    def default(self):
        sub_block = self.main_program.create_block()
        try:
            yield
        finally:
            self.main_program.rollback()
        self.has_default = True
        self.cases.append(('default', '', '', sub_block))

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        parent_block = self.main_program.block(self.parent_idx)
        parent_block.append_op(
            type='select', inputs={}, outputs={},
            attrs={
                'case_kinds': [c[0] for c in self.cases],
                'case_channels': [c[1] for c in self.cases],
                'case_values': [c[2] for c in self.cases],
                'sub_blocks': [c[3] for c in self.cases],
            })
        return True
