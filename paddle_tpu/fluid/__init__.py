"""paddle_tpu.fluid — the TPU-native Fluid-compatible frontend.

Re-designed from the reference python/paddle/fluid/__init__.py: the same
program-building API, but every program block compiles to XLA and runs on
TPU (fluid.TPUPlace()) instead of per-op CPU/CUDA kernels.
"""

from . import flags
from .flags import FLAGS
# env bootstrap first, so flags govern everything imported below
# (reference __init__.py:121-141 init_gflags tryfromenv)
flags.try_from_env(flags.TRYFROMENV)
from . import core
from .core import (CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace, LoDTensor,
                   LoDTensorArray, Scope, is_compiled_with_tpu,
                   is_compiled_with_cuda)
from . import framework
from .framework import (Program, Operator, Variable, Parameter,
                        default_main_program, default_startup_program,
                        program_guard, name_scope, get_var)
from . import executor
from .executor import Executor, global_scope, scope_guard, fetch_var
from . import parallel_executor
from .parallel_executor import ParallelExecutor, ExecutionStrategy, \
    BuildStrategy
from . import dataflow
from .dataflow import FeedPipeline
from . import trace
from . import initializer
from . import layers
from . import nets
from . import contrib
from . import optimizer
from . import backward
from .backward import append_backward, calc_gradient, gradients
from . import regularizer
from . import clip
from .clip import (ErrorClipByValue, GradientClipByValue, GradientClipByNorm,
                   GradientClipByGlobalNorm)
from .param_attr import ParamAttr, WeightNormParamAttr
from . import unique_name
from .data_feeder import DataFeeder
from . import io
from .io import (save_vars, save_params, save_persistables, load_vars,
                 load_params, load_persistables, save_inference_model,
                 load_inference_model, get_inference_program)
from . import metrics
from . import profiler
from . import lod_tensor
from .lod_tensor import create_lod_tensor, create_random_int_lodtensor
from . import recordio_writer
from . import transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig, \
    memory_optimize, release_memory, InferenceTranspiler, \
    Float16Transpiler
from . import evaluator
from . import concurrency
from . import amp
from .amp import amp_guard, enable_amp
from .concurrency import (Go, make_channel, channel_send, channel_recv,
                          channel_close, Select)
from . import debugger
from .trainer import (Trainer, BeginEpochEvent, EndEpochEvent,
                      BeginStepEvent, EndStepEvent, CheckpointConfig)
from .inferencer import Inferencer

Tensor = LoDTensor

__all__ = framework.__all__ + executor.__all__ + [
    'io', 'initializer', 'layers', 'nets', 'optimizer', 'backward',
    'regularizer', 'LoDTensor', 'CPUPlace', 'TPUPlace', 'CUDAPlace',
    'CUDAPinnedPlace', 'Tensor', 'ParamAttr', 'WeightNormParamAttr',
    'DataFeeder', 'clip', 'profiler', 'unique_name', 'flags', 'FLAGS',
    'dataflow', 'FeedPipeline', 'trace',
]
