"""Model save/load (reference: python/paddle/fluid/io.py).

The reference implements persistence as programs of ``save``/``load`` ops run
by the Executor (io.py:89-506, operators/save_op.cc).  Here the same public
API persists scope tensors directly from the host — params are pulled from
the device once and written as one ``.npz``-style combined file or one file
per variable (matching save_vars/save_combine semantics).  The serialized
inference model keeps the program-is-data contract: ``__model__`` holds the
serialized program (program_serde), params sit next to it.
"""

import json
import os

import numpy as np

from . import core
from .framework import Program, Parameter, Variable, default_main_program
from .executor import global_scope

__all__ = [
    'save_vars', 'save_params', 'save_persistables', 'load_vars',
    'load_params', 'load_persistables', 'save_inference_model',
    'load_inference_model', 'get_inference_program',
]


def is_persistable(var):
    return var.persistable


def is_parameter(var):
    return isinstance(var, Parameter)


def _scope_value(scope, name):
    var = scope.find_var(name)
    if var is None or var.value() is None:
        raise RuntimeError('variable %r has no value in scope' % name)
    val = var.value()
    if isinstance(val, core.LoDTensor):
        return val.numpy()
    return np.asarray(val)


def _save_one(path, arr):
    with open(path, 'wb') as f:
        np.lib.format.write_array(f, np.asarray(arr))


def _load_one(path):
    with open(path, 'rb') as f:
        return np.lib.format.read_array(f)


def save_vars(executor,
              dirname,
              main_program=None,
              vars=None,
              predicate=None,
              filename=None):
    """Save variables matching ``predicate`` (reference io.py:89)."""
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if filename is None:
        for var in vars:
            _save_one(
                os.path.join(dirname, var.name), _scope_value(scope, var.name))
    else:
        # combined file: npz (data-only), analog of save_combine_op
        blob = {v.name: _scope_value(scope, v.name) for v in vars}
        with open(os.path.join(dirname, filename), 'wb') as f:
            np.savez(f, **blob)


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(
        executor,
        dirname,
        main_program=main_program,
        vars=None,
        predicate=is_parameter,
        filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(
        executor,
        dirname,
        main_program=main_program,
        vars=None,
        predicate=is_persistable,
        filename=filename)


def load_vars(executor,
              dirname,
              main_program=None,
              vars=None,
              predicate=None,
              filename=None):
    """Load variables into the global scope (reference io.py:295)."""
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    scope = global_scope()
    if filename is None:
        for var in vars:
            arr = _load_one(os.path.join(dirname, var.name))
            scope.var(var.name).set_value(arr)
    else:
        with np.load(os.path.join(dirname, filename),
                     allow_pickle=False) as blob:
            for var in vars:
                scope.var(var.name).set_value(blob[var.name])


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(
        executor,
        dirname,
        main_program=main_program,
        predicate=is_parameter,
        filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(
        executor,
        dirname,
        main_program=main_program,
        predicate=is_persistable,
        filename=filename)


def get_inference_program(target_vars, main_program=None):
    if main_program is None:
        main_program = default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    pruned = main_program.prune(targets=target_vars)
    return pruned.inference_optimize()


def save_inference_model(dirname,
                         feeded_var_names,
                         target_vars,
                         executor,
                         main_program=None,
                         model_filename=None,
                         params_filename=None):
    """Prune to fetch targets, serialize program + params
    (reference io.py:561)."""
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    if main_program is None:
        main_program = default_main_program()
    os.makedirs(dirname, exist_ok=True)

    pruned = main_program.prune(targets=target_vars)
    inference_program = pruned.inference_optimize()
    fetch_var_names = [v.name for v in target_vars]

    model_filename = model_filename or '__model__'
    meta = {
        'program': inference_program.serialize_to_string().decode('utf-8'),
        'feed_var_names': list(feeded_var_names),
        'fetch_var_names': fetch_var_names,
    }
    with open(os.path.join(dirname, model_filename), 'w') as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, main_program, params_filename)
    return fetch_var_names


def load_inference_model(dirname,
                         executor,
                         model_filename=None,
                         params_filename=None):
    """Returns (program, feed_target_names, fetch_targets)
    (reference io.py:677)."""
    model_filename = model_filename or '__model__'
    with open(os.path.join(dirname, model_filename), 'r') as f:
        meta = json.load(f)
    program = Program.parse_from_string(meta['program'])
    load_persistables(executor, dirname, program, params_filename)
    feed_names = meta['feed_var_names']
    fetch_targets = [
        program.global_block().var(n) for n in meta['fetch_var_names']
    ]
    return program, feed_names, fetch_targets
