"""Model save/load (reference: python/paddle/fluid/io.py).

The reference implements persistence as programs of ``save``/``load`` ops run
by the Executor (io.py:89-506, operators/save_op.cc).  Here the same public
API persists scope tensors directly from the host — params are pulled from
the device once and written in the reference's version-0 LoDTensor stream
format (lod_tensor.cc:251), one file per variable or back-to-back in one
combined file (save_combine_op.cc).  ``__model__`` holds ProgramDesc
protobuf bytes with embedded feed/fetch ops — the reference's public model
contract (framework.proto:183, inference/io.cc:117); legacy JSON/npy/npz
artifacts from earlier rounds still load.
"""

import json
import os

import numpy as np

from . import core
from .framework import Program, Parameter, Variable, Operator, \
    default_main_program
from .executor import global_scope

__all__ = [
    'save_vars', 'save_params', 'save_persistables', 'load_vars',
    'load_params', 'load_persistables', 'save_inference_model',
    'load_inference_model', 'get_inference_program',
]


_NON_TENSOR_KINDS = frozenset([
    core.VarDesc.VarType.FEED_MINIBATCH, core.VarDesc.VarType.FETCH_LIST,
    core.VarDesc.VarType.READER, core.VarDesc.VarType.RAW,
    core.VarDesc.VarType.STEP_SCOPES, core.VarDesc.VarType.CHANNEL,
])


def is_persistable(var):
    # readers/feed/fetch holders are persistable program objects but carry
    # no tensor to serialize (reference io.py load_vars skips these kinds)
    return var.persistable and getattr(var, 'type',
                                       None) not in _NON_TENSOR_KINDS


def is_parameter(var):
    return isinstance(var, Parameter)


def _scope_value(scope, name):
    var = scope.find_var(name)
    if var is None or var.value() is None:
        raise RuntimeError('variable %r has no value in scope' % name)
    val = var.value()
    if isinstance(val, core.LoDTensor):
        return val.numpy()
    return np.asarray(val)


def _save_one(path, arr):
    # version-0 LoDTensor stream — the reference's parameter-file
    # contract (operators/save_op.cc -> lod_tensor.cc:251)
    from . import proto_serde
    with open(path, 'wb') as f:
        f.write(proto_serde.serialize_lod_tensor(np.asarray(arr)))


def _load_one(path):
    from . import proto_serde
    with open(path, 'rb') as f:
        if f.read(6) == b'\x93NUMPY':  # legacy npy artifact
            f.seek(0)
            return np.lib.format.read_array(f)
        f.seek(0)
        arr, _lod = proto_serde.read_lod_tensor(f)
        return arr


def check_tensor_matches_var(arr, var, source):
    """Guard against stream misassignment: combined files carry no names,
    so dims/dtype must agree with the program's var desc."""
    want_np = np.dtype(var.np_dtype)
    if arr.dtype != want_np:
        raise RuntimeError(
            '%s: dtype %s from file does not match var %r dtype %s' %
            (source, arr.dtype, var.name, want_np))
    want = tuple(var.shape or ())
    concrete_ok = (len(arr.shape) == len(want) and all(
        w in (-1, None) or int(w) == int(g)
        for w, g in zip(want, arr.shape)))
    if want and not concrete_ok:
        raise RuntimeError(
            '%s: shape %s from file does not match var %r shape %s' %
            (source, arr.shape, var.name, want))


def save_vars(executor,
              dirname,
              main_program=None,
              vars=None,
              predicate=None,
              filename=None):
    """Save variables matching ``predicate`` (reference io.py:89)."""
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if filename is None:
        for var in vars:
            _save_one(
                os.path.join(dirname, var.name), _scope_value(scope, var.name))
    else:
        # combined file: each var's LoDTensor stream back-to-back in var
        # order (reference operators/save_combine_op.cc)
        from . import proto_serde
        with open(os.path.join(dirname, filename), 'wb') as f:
            for v in vars:
                f.write(
                    proto_serde.serialize_lod_tensor(
                        _scope_value(scope, v.name)))


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(
        executor,
        dirname,
        main_program=main_program,
        vars=None,
        predicate=is_parameter,
        filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(
        executor,
        dirname,
        main_program=main_program,
        vars=None,
        predicate=is_persistable,
        filename=filename)


def load_vars(executor,
              dirname,
              main_program=None,
              vars=None,
              predicate=None,
              filename=None):
    """Load variables into the global scope (reference io.py:295)."""
    if vars is None:
        if main_program is None:
            main_program = default_main_program()
        vars = list(filter(predicate, main_program.list_vars()))
    scope = global_scope()
    if filename is None:
        for var in vars:
            arr = _load_one(os.path.join(dirname, var.name))
            scope.var(var.name).set_value(arr)
    else:
        path = os.path.join(dirname, filename)
        with open(path, 'rb') as f:
            magic = f.read(2)
        if magic == b'PK':  # legacy npz artifact
            with np.load(path, allow_pickle=False) as blob:
                for var in vars:
                    scope.var(var.name).set_value(blob[var.name])
        else:
            from . import proto_serde
            with open(path, 'rb') as f:
                for var in vars:
                    arr, _lod = proto_serde.read_lod_tensor(f)
                    check_tensor_matches_var(arr, var, path)
                    scope.var(var.name).set_value(arr)


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(
        executor,
        dirname,
        main_program=main_program,
        predicate=is_parameter,
        filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(
        executor,
        dirname,
        main_program=main_program,
        predicate=is_persistable,
        filename=filename)


def get_inference_program(target_vars, main_program=None):
    if main_program is None:
        main_program = default_main_program()
    if not isinstance(target_vars, list):
        target_vars = [target_vars]
    pruned = main_program.prune(targets=target_vars)
    return pruned.inference_optimize()


def save_inference_model(dirname,
                         feeded_var_names,
                         target_vars,
                         executor,
                         main_program=None,
                         model_filename=None,
                         params_filename=None):
    """Prune to fetch targets, serialize program + params
    (reference io.py:561)."""
    if isinstance(feeded_var_names, str):
        feeded_var_names = [feeded_var_names]
    if isinstance(target_vars, Variable):
        target_vars = [target_vars]
    if main_program is None:
        main_program = default_main_program()
    os.makedirs(dirname, exist_ok=True)

    pruned = main_program.prune(targets=target_vars)
    inference_program = pruned.inference_optimize()
    fetch_var_names = [v.name for v in target_vars]

    # params first, FROM THE PRUNED PROGRAM: combined files are
    # order-addressed streams, so the save order must be the var order
    # the loader will walk (the reference saves from the pruned program
    # too, io.py:633)
    save_persistables(executor, dirname, inference_program, params_filename)
    # the reference records feed/fetch targets INSIDE the program
    # (io.py:561 prepend_feed_ops/append_fetch_ops), so ``__model__`` is
    # pure ProgramDesc protobuf bytes — the public contract
    # (inference/io.cc:117 reads the file as a ProgramDesc)
    _prepend_feed_ops(inference_program, list(feeded_var_names))
    _append_fetch_ops(inference_program, fetch_var_names)
    model_filename = model_filename or '__model__'
    with open(os.path.join(dirname, model_filename), 'wb') as f:
        f.write(inference_program.serialize_to_string())
    return fetch_var_names


def _prepend_feed_ops(program, feed_target_names, feed_holder='feed'):
    """(reference io.py prepend_feed_ops)"""
    blk = program.global_block()
    blk.create_var(name=feed_holder,
                   type=core.VarDesc.VarType.FEED_MINIBATCH,
                   persistable=True)
    for i, name in enumerate(feed_target_names):
        op = Operator(blk, 'feed', inputs={'X': [feed_holder]},
                      outputs={'Out': [name]}, attrs={'col': i})
        blk.ops.insert(i, op)
    program._bump_version()


def _append_fetch_ops(program, fetch_target_names, fetch_holder='fetch'):
    """(reference io.py append_fetch_ops)"""
    blk = program.global_block()
    blk.create_var(name=fetch_holder,
                   type=core.VarDesc.VarType.FETCH_LIST,
                   persistable=True)
    for i, name in enumerate(fetch_target_names):
        blk.ops.append(
            Operator(blk, 'fetch', inputs={'X': [name]},
                     outputs={'Out': [fetch_holder]}, attrs={'col': i}))
    program._bump_version()


def _strip_feed_fetch_ops(program):
    """Recover (feed_names, fetch_names) from the embedded feed/fetch ops
    and remove them (this executor feeds/fetches by name)."""
    blk = program.global_block()
    feeds, fetches = {}, {}
    kept = []
    for op in blk.ops:
        if op.type == 'feed':
            feeds[op.attrs.get('col', len(feeds))] = op.output('Out')[0]
        elif op.type == 'fetch':
            fetches[op.attrs.get('col', len(fetches))] = op.input('X')[0]
        else:
            kept.append(op)
    blk.ops[:] = kept
    for holder in ('feed', 'fetch'):
        blk.vars.pop(holder, None)
    program._bump_version()
    return ([feeds[i] for i in sorted(feeds)],
            [fetches[i] for i in sorted(fetches)])


def load_inference_model(dirname,
                         executor,
                         model_filename=None,
                         params_filename=None):
    """Returns (program, feed_target_names, fetch_targets)
    (reference io.py:677)."""
    model_filename = model_filename or '__model__'
    with open(os.path.join(dirname, model_filename), 'rb') as f:
        data = f.read()
    if data[:1] == b'{':  # legacy JSON wrapper (pre-protobuf rounds)
        meta = json.loads(data.decode('utf-8'))
        program = Program.parse_from_string(meta['program'])
        feed_names = meta['feed_var_names']
        fetch_names = meta['fetch_var_names']
    else:
        program = Program.parse_from_string(data)
        feed_names, fetch_names = _strip_feed_fetch_ops(program)
    load_persistables(executor, dirname, program, params_filename)
    fetch_targets = [program.global_block().var(n) for n in fetch_names]
    return program, feed_names, fetch_targets
