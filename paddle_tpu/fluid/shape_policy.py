"""The ONE trailing-dim (sequence-length / resolution) ladder policy.

Every distinct trailing shape is one XLA compile, so a length-skewed
corpus or request stream must quantize its trailing extents onto a
bounded ladder.  Three consumers share this policy so the ladders stop
being parallel inventions (ISSUE 5):

  * ``executor._lod_to_padded`` — LoD feeds lower to padded [B, T, ...]
    with T = ``bucketed_len(max_len)`` (the original site);
  * ``serving.TrailingDimBuckets`` — the engine's per-feed trailing
    ladder, so mixed-length requests coalesce into shared executables;
  * ``executor.normalize_trailing_feed_list`` — run_multi /
    run_eval_multi feed_lists whose lots disagree on a seq feed's
    padded T re-quantize to one rung instead of failing uniformity.

``SEQ_BUCKET`` is the single tuning knob: multiples of it up to
16*SEQ_BUCKET (256 at the default 16), then geometric x1.25 steps
(lane-aligned).  tests/test_trailing_buckets.py pins the ladder values;
tests/test_recompile_bound.py pins the compile ceiling the policy
guarantees (<= 16 + log1.25(L/256) buckets, padding waste <= 25%).
"""

__all__ = ['SEQ_BUCKET', 'bucketed_len', 'seq_ladder']

SEQ_BUCKET = 16


def bucketed_len(max_len, bucket=SEQ_BUCKET):
    """Padded T for a batch/request whose longest row is ``max_len``.

    Multiples of ``bucket`` up to 16*bucket, then GEOMETRIC steps
    (x1.25, lane-aligned): a length-skewed corpus whose tail reaches L
    distinct maxima must not mint O(L/bucket) distinct shapes — each
    shape is one XLA compile and the Executor's LRU holds 64, so a
    linear ladder past ~1024 recompiles forever."""
    max_len = int(max_len)
    linear_top = 16 * bucket
    if max_len <= linear_top:
        return max(((max_len + bucket - 1) // bucket) * bucket, bucket)
    t = linear_top
    while t < max_len:
        t = ((t + (t >> 2)) + bucket - 1) // bucket * bucket
    return t


def seq_ladder(top, bucket=SEQ_BUCKET):
    """The ladder ``bucketed_len`` quantizes onto, materialized up to
    (and including) the rung covering ``top`` — the warm/precompile
    form of the same policy."""
    rungs, t = [], bucket
    while True:
        rungs.append(t)
        if t >= int(top):
            return rungs
        t = bucketed_len(t + 1, bucket)
