"""Optimizer family (reference: python/paddle/fluid/optimizer.py:38).

Each optimizer appends per-parameter update ops (sgd/adam/...) to the main
program — identical graph structure to the reference's
``_create_optimization_pass`` (optimizer.py:196) — which then compile into
the same fused XLA step as the rest of the block.
"""

from collections import defaultdict
from contextlib import contextmanager

from . import framework
from . import unique_name
from .backward import append_backward
from .clip import append_gradient_clip_ops, error_clip_callback
from .framework import program_guard, Variable
from .initializer import Constant
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops

__all__ = [
    'SGD', 'Momentum', 'Adagrad', 'Adam', 'Adamax', 'DecayedAdagrad',
    'Ftrl', 'SGDOptimizer', 'MomentumOptimizer', 'AdagradOptimizer',
    'AdamOptimizer', 'AdamaxOptimizer', 'DecayedAdagradOptimizer',
    'RMSPropOptimizer', 'FtrlOptimizer', 'Adadelta', 'AdadeltaOptimizer',
    'ModelAverage', 'Optimizer', 'ProximalGD', 'ProximalGDOptimizer',
    'ProximalAdagrad', 'ProximalAdagradOptimizer',
]


class Optimizer(object):
    def __init__(self, learning_rate, regularization=None, name=None):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError('learning rate should be float or Variable')
        self._name = name
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_map = dict()
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[
                framework.default_main_program()] = self._learning_rate
        # {accum_name: {param_name: accum_var}}
        self._accumulators = defaultdict(lambda: dict())
        self.helper = None

    def _create_global_learning_rate(self):
        lr = self._global_learning_rate()
        if isinstance(lr, Variable):
            return
        if not isinstance(self._learning_rate, float):
            raise TypeError('learning rate should be float or Variable')
        from .layers import tensor
        self._learning_rate_map[framework.default_main_program()] = \
            tensor.create_global_var(
                name=unique_name.generate('learning_rate'),
                shape=[1],
                value=float(self._learning_rate),
                dtype='float32',
                persistable=True)

    def _global_learning_rate(self, program=None):
        if program is None:
            program = framework.default_main_program()
        return self._learning_rate_map.get(program, None)

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError()

    def _create_param_lr(self, param_and_grad):
        param_lr = param_and_grad[0].optimize_attr['learning_rate']
        if param_lr == 1.0:
            return self._global_learning_rate()
        from .layers import ops as _ops
        with framework.program_guard(framework.default_main_program(), None):
            return _ops.scale(self._global_learning_rate(), scale=param_lr)

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block):
        pass

    def _add_accumulator(self,
                         name,
                         param,
                         dtype=None,
                         fill_value=0.0,
                         shape=None):
        if self._name is not None:
            name = self._name + '_' + name
        if name in self._accumulators and \
                param.name in self._accumulators[name]:
            raise Exception('Accumulator %s already exists for parameter %s' %
                            (name, param.name))
        if shape is None:
            shape = param.shape
        assert self.helper is not None
        var_name = unique_name.generate(param.name + '_' + name)
        var = self.helper.create_global_variable(
            name=var_name,
            persistable=True,
            dtype=dtype or param.dtype,
            shape=shape)
        # record the owning param so placement passes (e.g. the sparse
        # DistributeTranspiler rewrite) can co-locate accumulators with
        # their param without guessing from names
        var._accumulator_for = param.name
        self.helper.set_variable_initializer(
            var, initializer=Constant(value=float(fill_value)))
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        if self._name is not None:
            name = self._name + '_' + name
        if name not in self._accumulators or \
                param.name not in self._accumulators[name]:
            raise Exception('Accumulator %s does not exist for parameter %s' %
                            (name, param.name))
        return self._accumulators[name][param.name]

    def _create_optimization_pass(self,
                                  parameters_and_grads,
                                  loss,
                                  startup_program=None):
        program = loss.block.program
        with framework.program_guard(program, startup_program):
            global_block = program.global_block()
            optimize_ops = []
            self.helper = LayerHelper(self.__class__.__name__)
            self._create_accumulators(
                global_block, [p[0] for p in parameters_and_grads])
            self._create_global_learning_rate()
            for param_and_grad in parameters_and_grads:
                if param_and_grad[1] is None:
                    continue
                if param_and_grad[0].trainable:
                    optimize_op = self._append_optimize_op(
                        global_block, param_and_grad)
                    optimize_ops.append(optimize_op)
            self._finish_update(global_block)
        return optimize_ops

    def minimize(self,
                 loss,
                 startup_program=None,
                 parameter_list=None,
                 no_grad_set=None):
        """backward + regularization/clip + update ops
        (reference optimizer.py:253)."""
        params_grads = append_backward(loss, parameter_list, no_grad_set,
                                       [error_clip_callback])
        with framework.program_guard(loss.block.program, startup_program):
            params_grads = append_gradient_clip_ops(params_grads)
            params_grads = append_regularization_ops(params_grads,
                                                     self.regularization)
        optimize_ops = self._create_optimization_pass(params_grads, loss,
                                                      startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        super(SGDOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'sgd'

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'LearningRate': [self._create_param_lr(param_and_grad)]
            },
            outputs={'ParamOut': [param_and_grad[0]]})


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = 'velocity'

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super(MomentumOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'momentum'
        self._momentum = momentum
        self._use_nesterov = bool(use_nesterov)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity_acc = self._get_accumulator(self._velocity_acc_str,
                                             param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'Velocity': [velocity_acc],
                'LearningRate': [self._create_param_lr(param_and_grad)]
            },
            outputs={
                'ParamOut': [param_and_grad[0]],
                'VelocityOut': [velocity_acc]
            },
            attrs={'mu': self._momentum,
                   'use_nesterov': self._use_nesterov})


class AdagradOptimizer(Optimizer):
    _moment_acc_str = 'moment'

    def __init__(self, learning_rate, epsilon=1.0e-6, **kwargs):
        super(AdagradOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'adagrad'
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'Moment': [moment_acc],
                'LearningRate': [self._create_param_lr(param_and_grad)]
            },
            outputs={
                'ParamOut': [param_and_grad[0]],
                'MomentOut': [moment_acc]
            },
            attrs={'epsilon': self._epsilon})


class AdamOptimizer(Optimizer):
    _moment1_acc_str = 'moment1'
    _moment2_acc_str = 'moment2'

    def __init__(self,
                 learning_rate=0.001,
                 beta1=0.9,
                 beta2=0.999,
                 epsilon=1e-8,
                 **kwargs):
        super(AdamOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'adam'
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        main_block = block.program.global_block()
        self._beta1_pow_acc = self.helper.create_global_variable(
            name=unique_name.generate('beta1_pow_acc'),
            dtype='float32',
            shape=[1],
            persistable=True)
        self.helper.set_variable_initializer(
            self._beta1_pow_acc, initializer=Constant(self._beta1))
        self._beta2_pow_acc = self.helper.create_global_variable(
            name=unique_name.generate('beta2_pow_acc'),
            dtype='float32',
            shape=[1],
            persistable=True)
        self.helper.set_variable_initializer(
            self._beta2_pow_acc, initializer=Constant(self._beta2))
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment1 = self._get_accumulator(self._moment1_acc_str,
                                        param_and_grad[0])
        moment2 = self._get_accumulator(self._moment2_acc_str,
                                        param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'LearningRate': [self._create_param_lr(param_and_grad)],
                'Moment1': [moment1],
                'Moment2': [moment2],
                'Beta1Pow': [self._beta1_pow_acc],
                'Beta2Pow': [self._beta2_pow_acc]
            },
            outputs={
                'ParamOut': [param_and_grad[0]],
                'Moment1Out': [moment1],
                'Moment2Out': [moment2]
            },
            attrs={
                'beta1': self._beta1,
                'beta2': self._beta2,
                'epsilon': self._epsilon
            })

    def _finish_update(self, block):
        """beta_pow *= beta, once per step (reference optimizer.py Adam)."""
        for acc, beta in ((self._beta1_pow_acc, self._beta1),
                          (self._beta2_pow_acc, self._beta2)):
            block.append_op(
                type='scale',
                inputs={'X': [acc]},
                outputs={'Out': [acc]},
                attrs={'scale': beta})


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = 'moment'
    _inf_norm_acc_str = 'inf_norm'

    def __init__(self,
                 learning_rate=0.001,
                 beta1=0.9,
                 beta2=0.999,
                 epsilon=1e-8,
                 **kwargs):
        super(AdamaxOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'adamax'
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        self._beta1_pow_acc = self.helper.create_global_variable(
            name=unique_name.generate('beta1_pow_acc'),
            dtype='float32',
            shape=[1],
            persistable=True)
        self.helper.set_variable_initializer(
            self._beta1_pow_acc, initializer=Constant(self._beta1))
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str,
                                       param_and_grad[0])
        inf_norm = self._get_accumulator(self._inf_norm_acc_str,
                                         param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'LearningRate': [self._create_param_lr(param_and_grad)],
                'Moment': [moment],
                'InfNorm': [inf_norm],
                'Beta1Pow': [self._beta1_pow_acc]
            },
            outputs={
                'ParamOut': [param_and_grad[0]],
                'MomentOut': [moment],
                'InfNormOut': [inf_norm]
            },
            attrs={
                'beta1': self._beta1,
                'beta2': self._beta2,
                'epsilon': self._epsilon
            })

    def _finish_update(self, block):
        block.append_op(
            type='scale',
            inputs={'X': [self._beta1_pow_acc]},
            outputs={'Out': [self._beta1_pow_acc]},
            attrs={'scale': self._beta1})


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = 'moment'

    def __init__(self, learning_rate, decay=0.95, epsilon=1.0e-6, **kwargs):
        super(DecayedAdagradOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'decayed_adagrad'
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'Moment': [moment_acc],
                'LearningRate': [self._create_param_lr(param_and_grad)]
            },
            outputs={
                'ParamOut': [param_and_grad[0]],
                'MomentOut': [moment_acc]
            },
            attrs={'epsilon': self._epsilon,
                   'decay': self._decay})


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = '_avg_squared_grad'
    _avg_squared_update_acc_str = '_avg_squared_update'

    def __init__(self, learning_rate, epsilon=1.0e-6, rho=0.95, **kwargs):
        super(AdadeltaOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'adadelta'
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        avg_squared_grad_acc = self._get_accumulator(
            self._avg_squared_grad_acc_str, param_and_grad[0])
        avg_squared_update_acc = self._get_accumulator(
            self._avg_squared_update_acc_str, param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'AvgSquaredGrad': [avg_squared_grad_acc],
                'AvgSquaredUpdate': [avg_squared_update_acc]
            },
            outputs={
                'ParamOut': [param_and_grad[0]],
                'AvgSquaredGradOut': [avg_squared_grad_acc],
                'AvgSquaredUpdateOut': [avg_squared_update_acc]
            },
            attrs={'epsilon': self._epsilon,
                   'rho': self._rho})


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = 'momentum'
    _mean_square_acc_str = 'mean_square'

    def __init__(self,
                 learning_rate,
                 rho=0.95,
                 epsilon=1.0e-6,
                 momentum=0.0,
                 **kwargs):
        super(RMSPropOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'rmsprop'
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum_acc = self._get_accumulator(self._momentum_acc_str,
                                             param_and_grad[0])
        mean_square_acc = self._get_accumulator(self._mean_square_acc_str,
                                                param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'Moment': [momentum_acc],
                'MeanSquare': [mean_square_acc],
                'LearningRate': [self._create_param_lr(param_and_grad)]
            },
            outputs={
                'ParamOut': [param_and_grad[0]],
                'MomentOut': [momentum_acc],
                'MeanSquareOut': [mean_square_acc]
            },
            attrs={
                'epsilon': self._epsilon,
                'decay': self._rho,
                'momentum': self._momentum
            })


class FtrlOptimizer(Optimizer):
    _squared_acc_str = 'squared'
    _linear_acc_str = 'linear'

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kwargs):
        super(FtrlOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'ftrl'
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        squared_acc = self._get_accumulator(self._squared_acc_str,
                                            param_and_grad[0])
        linear_acc = self._get_accumulator(self._linear_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'SquaredAccumulator': [squared_acc],
                'LinearAccumulator': [linear_acc],
                'LearningRate': [self._create_param_lr(param_and_grad)]
            },
            outputs={
                'ParamOut': [param_and_grad[0]],
                'SquaredAccumOut': [squared_acc],
                'LinearAccumOut': [linear_acc]
            },
            attrs={
                'l1': self._l1,
                'l2': self._l2,
                'lr_power': self._lr_power
            })


class ProximalGDOptimizer(Optimizer):
    """Proximal gradient descent with L1/L2 shrinkage (reference
    optimizer.py-era operators/proximal_gd_op.cc)."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super(ProximalGDOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'proximal_gd'
        self._l1 = l1
        self._l2 = l2

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'LearningRate': [self._create_param_lr(param_and_grad)]
            },
            outputs={'ParamOut': [param_and_grad[0]]},
            attrs={'l1': self._l1,
                   'l2': self._l2})


class ProximalAdagradOptimizer(Optimizer):
    """Adagrad with proximal L1/L2 shrinkage (reference
    operators/proximal_adagrad_op.cc)."""
    _moment_acc_str = 'moment'

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super(ProximalAdagradOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = 'proximal_adagrad'
        self._l1 = l1
        self._l2 = l2

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={
                'Param': [param_and_grad[0]],
                'Grad': [param_and_grad[1]],
                'Moment': [moment_acc],
                'LearningRate': [self._create_param_lr(param_and_grad)]
            },
            outputs={'ParamOut': [param_and_grad[0]],
                     'MomentOut': [moment_acc]},
            attrs={'l1': self._l1,
                   'l2': self._l2})


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
ProximalGD = ProximalGDOptimizer
ProximalAdagrad = ProximalAdagradOptimizer


class ModelAverage(Optimizer):
    """Running average of parameters (reference optimizer.py:1145 +
    operators/average_accumulates_op.cc).  Construct AFTER the training
    optimizer's minimize(): appends accumulate ops to the main program;
    ``with model_average.apply(exe):`` swaps params for their windowed
    average (inference/eval), restore() puts the live params back."""

    def __init__(self,
                 average_window_rate,
                 min_average_window=10000,
                 max_average_window=10000,
                 **kwargs):
        super(ModelAverage, self).__init__(learning_rate=0.0, **kwargs)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params = [
            p for p in
            framework.default_main_program().global_block()
            .all_parameters() if p.trainable
        ]
        self.helper = LayerHelper('model_average')
        with framework.program_guard(framework.default_main_program(),
                                     framework.default_startup_program()):
            for param in self.params:
                self._append_average_accumulate_op(param)

        self.apply_program = framework.Program()
        self.restore_program = framework.Program()
        with framework.program_guard(self.apply_program):
            for param in self.params:
                self._add_average_apply_op(param)
        with framework.program_guard(self.restore_program):
            for param in self.params:
                self._add_average_restore_op(param)

    def _append_average_accumulate_op(self, param):
        self._add_accumulator('sum_1', param)
        self._add_accumulator('sum_2', param)
        self._add_accumulator('sum_3', param)
        self._add_accumulator('num_accumulates', param, dtype='int64',
                              shape=[1])
        self._add_accumulator('old_num_accumulates', param, dtype='int64',
                              shape=[1])
        self._add_accumulator('num_updates', param, dtype='int64',
                              shape=[1])
        accs = {n: self._get_accumulator(n, param) for n in
                ('sum_1', 'sum_2', 'sum_3', 'num_accumulates',
                 'old_num_accumulates', 'num_updates')}
        self.helper.append_op(
            type='average_accumulates',
            inputs={
                'param': [param],
                'in_sum_1': [accs['sum_1']],
                'in_sum_2': [accs['sum_2']],
                'in_sum_3': [accs['sum_3']],
                'in_num_accumulates': [accs['num_accumulates']],
                'in_old_num_accumulates': [accs['old_num_accumulates']],
                'in_num_updates': [accs['num_updates']],
            },
            outputs={
                'out_sum_1': [accs['sum_1']],
                'out_sum_2': [accs['sum_2']],
                'out_sum_3': [accs['sum_3']],
                'out_num_accumulates': [accs['num_accumulates']],
                'out_old_num_accumulates': [accs['old_num_accumulates']],
                'out_num_updates': [accs['num_updates']],
            },
            attrs={
                'average_window': self.average_window,
                'min_average_window': self.min_average_window,
                'max_average_window': self.max_average_window,
            })

    def _ref(self, program, var):
        """Mirror a var of the training program into `program`."""
        return program.global_block().create_var(
            name=var.name, shape=var.shape, dtype=var.dtype,
            persistable=True)

    def _add_average_apply_op(self, param):
        block = framework.default_main_program().global_block()
        p = self._ref(block.program, param)
        backup = block.create_var(
            name=param.name + '@MA_BACKUP', shape=param.shape,
            dtype=param.dtype, persistable=True)
        sum_1 = self._ref(block.program,
                          self._get_accumulator('sum_1', param))
        sum_2 = self._ref(block.program,
                          self._get_accumulator('sum_2', param))
        sum_3 = self._ref(block.program,
                          self._get_accumulator('sum_3', param))
        num_acc = self._ref(
            block.program, self._get_accumulator('num_accumulates', param))
        old_num_acc = self._ref(
            block.program,
            self._get_accumulator('old_num_accumulates', param))
        from . import layers
        layers.assign(input=p, output=backup)
        total = layers.sums([sum_1, sum_2, sum_3])
        count = layers.cast(
            layers.sums([num_acc, old_num_acc]), dtype=param.dtype)
        avg = layers.elementwise_div(
            x=total, y=layers.clip(count, min=1.0, max=1e30))
        layers.assign(input=avg, output=p)

    def _add_average_restore_op(self, param):
        block = framework.default_main_program().global_block()
        p = self._ref(block.program, param)
        backup = block.create_var(
            name=param.name + '@MA_BACKUP', shape=param.shape,
            dtype=param.dtype, persistable=True)
        from . import layers
        layers.assign(input=backup, output=p)

    @contextmanager
    def apply(self, executor, need_restore=True):
        executor.run(self.apply_program)
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor):
        executor.run(self.restore_program)
