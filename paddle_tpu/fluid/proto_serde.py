"""framework.proto-compatible serialization — the public contract.

The reference's model artifacts are protobuf ``ProgramDesc`` bytes
(framework/framework.proto:183; inference loads ``__model__`` at
inference/io.cc:117) and version-0 LoDTensor streams
(framework/lod_tensor.cc:251 SerializeToStream, tensor_util.cc:244
TensorToStream).  This module speaks both formats with a hand-written
proto2 wire codec — no generated code, no protoc build step — so
programs and parameters saved here load under the reference contract
and vice versa.

Wire facts used (proto2):
  tag = (field_number << 3) | wire_type; wire types: 0 varint,
  2 length-delimited, 5 fixed32 (float).  Repeated scalar fields are
  emitted unpacked (one tag per element), proto2's default.  Signed
  int32/int64 values are encoded as 64-bit two's-complement varints.

Field numbers (framework.proto):
  ProgramDesc.blocks=1
  BlockDesc: idx=1 parent_idx=2 vars=3 ops=4 forward_block_idx=5
  VarDesc: name=1 type=2 persistable=3
  VarType: type=1 selected_rows=2 lod_tensor=3 tensor_array=4
           reader=5 channel=6
  VarType.TensorDesc: data_type=1 dims=2
  VarType.LoDTensorDesc: tensor=1 lod_level=2
  VarType.ChannelDesc: data_type=1 capacity=2
  OpDesc: inputs=1 outputs=2 type=3 attrs=4 is_target=5
  OpDesc.Var: parameter=1 arguments=2
  OpDesc.Attr: name=1 type=2 i=3 f=4 s=5 ints=6 floats=7 strings=8
               b=10 bools=11 block_idx=12 l=13 blocks_idx=14
  AttrType enum: INT=0 FLOAT=1 STRING=2 INTS=3 FLOATS=4 STRINGS=5
                 BOOLEAN=6 BOOLEANS=7 BLOCK=8 LONG=9 BLOCKS=10
"""

import struct

import numpy as np

from . import core

__all__ = [
    'serialize_program', 'deserialize_program', 'serialize_lod_tensor',
    'deserialize_lod_tensor', 'read_lod_tensor'
]

_INT32_MIN, _INT32_MAX = -2**31, 2**31 - 1


# ----------------------------------------------------------------------------
# proto2 wire primitives
# ----------------------------------------------------------------------------
def _varint(value):
    if value < 0:
        value &= (1 << 64) - 1  # two's-complement 64-bit
    out = bytearray()
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _tag(field, wire):
    return _varint((field << 3) | wire)


def _field_varint(field, value):
    return _tag(field, 0) + _varint(int(value))


def _field_bytes(field, data):
    return _tag(field, 2) + _varint(len(data)) + data


def _field_str(field, s):
    return _field_bytes(field, s.encode('utf-8'))


def _field_float(field, value):
    return _tag(field, 5) + struct.pack('<f', float(value))


class _Reader(object):
    def __init__(self, data):
        self.data = data
        self.pos = 0

    def eof(self):
        return self.pos >= len(self.data)

    def varint(self):
        result = 0
        shift = 0
        while True:
            byte = self.data[self.pos]
            self.pos += 1
            result |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return result
            shift += 7

    def signed(self):
        v = self.varint()
        return v - (1 << 64) if v >= (1 << 63) else v

    def ld(self):
        n = self.varint()
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def fixed32(self):
        v = struct.unpack_from('<f', self.data, self.pos)[0]
        self.pos += 4
        return v

    def skip(self, wire):
        if wire == 0:
            self.varint()
        elif wire == 1:
            self.pos += 8
        elif wire == 2:
            self.ld()
        elif wire == 5:
            self.pos += 4
        else:
            raise ValueError('unsupported wire type %d' % wire)

    def fields(self):
        """Yield (field_number, wire_type, value) triples; value is the
        raw varint / bytes / float depending on wire type."""
        while not self.eof():
            key = self.varint()
            field, wire = key >> 3, key & 7
            if wire == 0:
                yield field, wire, self.signed()
            elif wire == 2:
                yield field, wire, self.ld()
            elif wire == 5:
                yield field, wire, self.fixed32()
            else:
                self.skip(wire)


# ----------------------------------------------------------------------------
# VarDesc / VarType
# ----------------------------------------------------------------------------
_VT = core.VarDesc.VarType


def _tensor_desc(dtype_enum, dims):
    out = _field_varint(1, dtype_enum)
    for d in dims:
        out += _field_varint(2, int(d))
    return out


def _lod_tensor_desc(dtype_enum, dims, lod_level):
    out = _field_bytes(1, _tensor_desc(dtype_enum, dims))
    if lod_level:
        out += _field_varint(2, int(lod_level))
    return out


def _var_type_bytes(v):
    out = _field_varint(1, v.type)
    dims = [d if d is not None else -1 for d in (v.shape or ())]
    if v.type == _VT.LOD_TENSOR:
        out += _field_bytes(
            3, _lod_tensor_desc(v.dtype, dims, v.lod_level))
    elif v.type == _VT.SELECTED_ROWS:
        out += _field_bytes(2, _tensor_desc(v.dtype, dims))
    elif v.type == _VT.LOD_TENSOR_ARRAY:
        out += _field_bytes(
            4, _lod_tensor_desc(v.dtype, dims, v.lod_level))
    elif v.type == _VT.READER:
        out += _field_bytes(5, b'')
    elif v.type == _VT.CHANNEL:
        cap = getattr(v, 'capacity', None) or 0
        out += _field_bytes(
            6, _field_varint(1, v.dtype) + _field_varint(2, cap))
    return out


def _var_desc_bytes(v):
    out = _field_str(1, v.name)
    out += _field_bytes(2, _var_type_bytes(v))
    if v.persistable:
        out += _field_varint(3, 1)
    return out


def _parse_tensor_desc(data):
    dtype, dims = _VT.FP32, []
    for field, wire, val in _Reader(data).fields():
        if field == 1:
            dtype = val
        elif field == 2:
            dims.append(val)
    return dtype, dims


def _parse_lod_tensor_desc(data):
    dtype, dims, lod_level = _VT.FP32, [], 0
    for field, wire, val in _Reader(data).fields():
        if field == 1:
            dtype, dims = _parse_tensor_desc(val)
        elif field == 2:
            lod_level = val
    return dtype, dims, lod_level


def _parse_var_type(data):
    kind, dtype, dims, lod_level, capacity = _VT.LOD_TENSOR, _VT.FP32, [], \
        0, None
    for field, wire, val in _Reader(data).fields():
        if field == 1:
            kind = val
        elif field in (2, ):  # selected_rows TensorDesc
            dtype, dims = _parse_tensor_desc(val)
        elif field in (3, 4):  # lod_tensor / tensor_array
            dtype, dims, lod_level = _parse_lod_tensor_desc(val)
        elif field == 6:  # channel
            for f2, w2, v2 in _Reader(val).fields():
                if f2 == 1:
                    dtype = v2
                elif f2 == 2:
                    capacity = v2
    return kind, dtype, dims, lod_level, capacity


def _parse_var_desc(data):
    name, vtype, persistable = '', b'', False
    for field, wire, val in _Reader(data).fields():
        if field == 1:
            name = val.decode('utf-8')
        elif field == 2:
            vtype = val
        elif field == 3:
            persistable = bool(val)
    kind, dtype, dims, lod_level, capacity = _parse_var_type(vtype)
    return dict(name=name, type=kind, dtype=dtype, shape=dims,
                lod_level=lod_level, capacity=capacity,
                persistable=persistable)


# ----------------------------------------------------------------------------
# OpDesc attrs
# ----------------------------------------------------------------------------
def _is_int(x):
    return isinstance(x, (int, np.integer)) and not isinstance(
        x, (bool, np.bool_))


def _attr_bytes(name, value):
    from .framework import Block
    out = _field_str(1, name)
    if isinstance(value, np.ndarray):
        value = value.tolist()
    if isinstance(value, Block):
        out += _field_varint(2, 8)  # BLOCK
        out += _field_varint(12, value.idx)
    elif isinstance(value, (bool, np.bool_)):
        out += _field_varint(2, 6)  # BOOLEAN
        out += _field_varint(10, 1 if value else 0)
    elif _is_int(value):
        if _INT32_MIN <= int(value) <= _INT32_MAX:
            out += _field_varint(2, 0)  # INT
            out += _field_varint(3, int(value))
        else:
            out += _field_varint(2, 9)  # LONG
            out += _field_varint(13, int(value))
    elif isinstance(value, (float, np.floating)):
        out += _field_varint(2, 1)  # FLOAT
        out += _field_float(4, value)
    elif isinstance(value, str):
        out += _field_varint(2, 2)  # STRING
        out += _field_str(5, value)
    elif isinstance(value, (list, tuple)):
        items = list(value)
        if items and isinstance(items[0], Block):
            out += _field_varint(2, 10)  # BLOCKS
            for b in items:
                out += _field_varint(14, b.idx)
        elif items and isinstance(items[0], (bool, np.bool_)):
            out += _field_varint(2, 7)  # BOOLEANS
            for b in items:
                out += _field_varint(11, 1 if b else 0)
        elif items and isinstance(items[0], (float, np.floating)):
            out += _field_varint(2, 4)  # FLOATS
            for f in items:
                out += _field_float(7, f)
        elif items and isinstance(items[0], str):
            out += _field_varint(2, 5)  # STRINGS
            for s in items:
                out += _field_str(8, s)
        else:
            # ints — and the EMPTY-list fallback.  The wire attr type is
            # inferred from the first element because the in-memory attr
            # is a plain Python list; an empty FLOATS/STRINGS/BOOLEANS
            # attr therefore degrades to INTS-with-no-elements on the
            # wire.  Our own loader treats any empty list identically;
            # a strict foreign OpDesc type-checker could reject such a
            # program (documented delta, ADVICE r2 #1).
            out += _field_varint(2, 3)
            for i in items:
                out += _field_varint(6, int(i))
    else:
        raise TypeError('attr %r: unserializable value %r (%s)' %
                        (name, value, type(value).__name__))
    return out


def _parse_attr(data, program):
    name = None
    atype = 0
    scalars = {}
    ints, floats, strings, bools, blocks_idx = [], [], [], [], []
    for field, wire, val in _Reader(data).fields():
        if field == 1:
            name = val.decode('utf-8')
        elif field == 2:
            atype = val
        elif field == 3:
            scalars['i'] = val
        elif field == 4:
            scalars['f'] = val
        elif field == 5:
            scalars['s'] = val.decode('utf-8')
        elif field == 6:
            ints.append(val)
        elif field == 7:
            floats.append(val)
        elif field == 8:
            strings.append(val.decode('utf-8'))
        elif field == 10:
            scalars['b'] = bool(val)
        elif field == 11:
            bools.append(bool(val))
        elif field == 12:
            scalars['block_idx'] = val
        elif field == 13:
            scalars['l'] = val
        elif field == 14:
            blocks_idx.append(val)
    value = {
        0: lambda: scalars.get('i', 0),
        1: lambda: scalars.get('f', 0.0),
        2: lambda: scalars.get('s', ''),
        3: lambda: ints,
        4: lambda: floats,
        5: lambda: strings,
        6: lambda: scalars.get('b', False),
        7: lambda: bools,
        8: lambda: program.block(scalars['block_idx']),
        9: lambda: scalars.get('l', 0),
        10: lambda: [program.block(i) for i in blocks_idx],
    }[atype]()
    return name, value


def _op_var_bytes(field, parameter, arguments):
    body = _field_str(1, parameter)
    for a in arguments:
        body += _field_str(2, a)
    return _field_bytes(field, body)


def _op_desc_bytes(op):
    out = b''
    for param, args in op.inputs.items():
        out += _op_var_bytes(1, param, args)
    for param, args in op.outputs.items():
        out += _op_var_bytes(2, param, args)
    out += _field_str(3, op.type)
    for name, value in op.attrs.items():
        if name in _MUTABLE_RUNTIME_ATTRS:
            continue
        out += _field_bytes(4, _attr_bytes(name, value))
    return out


# per-run mutable counters, not program structure
_MUTABLE_RUNTIME_ATTRS = frozenset(['__print_count__'])


def _parse_op_var(data):
    param, args = '', []
    for field, wire, val in _Reader(data).fields():
        if field == 1:
            param = val.decode('utf-8')
        elif field == 2:
            args.append(val.decode('utf-8'))
    return param, args


def _parse_op_desc(data, program):
    op_type, inputs, outputs, raw_attrs = '', {}, {}, []
    for field, wire, val in _Reader(data).fields():
        if field == 1:
            p, a = _parse_op_var(val)
            inputs[p] = a
        elif field == 2:
            p, a = _parse_op_var(val)
            outputs[p] = a
        elif field == 3:
            op_type = val.decode('utf-8')
        elif field == 4:
            raw_attrs.append(val)
    attrs = {}
    for raw in raw_attrs:
        name, value = _parse_attr(raw, program)
        attrs[name] = value
    return op_type, inputs, outputs, attrs


# ----------------------------------------------------------------------------
# ProgramDesc
# ----------------------------------------------------------------------------
def serialize_program(program):
    """Program -> framework.proto ProgramDesc bytes."""
    out = b''
    for blk in program.blocks:
        body = _field_varint(1, blk.idx)
        # root block parent is -1 in the reference's emitted bytes
        # (signed 64-bit varint); sub-blocks carry their real parent
        parent = (blk.parent_idx if blk.parent_idx is not None
                  and blk.parent_idx >= 0 else -1)
        body += _field_varint(2, parent)
        for v in blk.vars.values():
            body += _field_bytes(3, _var_desc_bytes(v))
        for op in blk.ops:
            body += _field_bytes(4, _op_desc_bytes(op))
        out += _field_bytes(1, body)
    return out


def deserialize_program(data):
    """framework.proto ProgramDesc bytes -> Program."""
    from .framework import Program, Block, Variable, Operator
    raw_blocks = [val for field, wire, val in _Reader(data).fields()
                  if field == 1]
    # first pass: block skeletons, so sub_block attrs can resolve
    parsed = []
    for raw in raw_blocks:
        idx, parent, raw_vars, raw_ops = 0, 0, [], []
        for field, wire, val in _Reader(raw).fields():
            if field == 1:
                idx = val
            elif field == 2:
                parent = val
            elif field == 3:
                raw_vars.append(val)
            elif field == 4:
                raw_ops.append(val)
        parsed.append((idx, parent, raw_vars, raw_ops))
    program = Program()
    while len(program.blocks) < len(parsed):
        i = len(program.blocks)
        program.blocks.append(Block(program, i, parsed[i][1]))
    program.current_block_idx = 0
    for (idx, parent, raw_vars, raw_ops), blk in zip(parsed,
                                                     program.blocks):
        blk.parent_idx = parent if idx != 0 else -1
        for raw in raw_vars:
            kw = _parse_var_desc(raw)
            capacity = kw.pop('capacity', None)
            v = Variable(blk, **kw)
            if capacity:
                v.capacity = capacity
            blk.vars[v.name] = v
        for raw in raw_ops:
            op_type, inputs, outputs, attrs = _parse_op_desc(raw, program)
            blk.ops.append(
                Operator(blk, op_type, inputs=inputs, outputs=outputs,
                         attrs=attrs))
    program._bump_version()
    return program


# ----------------------------------------------------------------------------
# LoDTensor / Tensor streams (lod_tensor.cc:251, tensor_util.cc:244)
# ----------------------------------------------------------------------------
def _np_dtype_enum(arr):
    return core.convert_np_dtype_to_dtype_(arr.dtype)


def serialize_lod_tensor(arr, lod=()):
    """ndarray (+ offset-based LoD levels) -> version-0 stream bytes."""
    arr = np.asarray(arr)
    shape = arr.shape  # before ascontiguousarray promotes 0-d to (1,)
    arr = np.ascontiguousarray(arr).reshape(shape)
    out = [struct.pack('<I', 0)]               # LoDTensor version
    out.append(struct.pack('<Q', len(lod)))    # lod level count
    for level in lod:
        lv = np.asarray(level, np.uint64)
        out.append(struct.pack('<Q', lv.nbytes))
        out.append(lv.tobytes())
    out.append(struct.pack('<I', 0))           # Tensor version
    desc = _tensor_desc(_np_dtype_enum(arr), arr.shape)
    out.append(struct.pack('<i', len(desc)))
    out.append(desc)
    out.append(arr.tobytes())
    return b''.join(out)


def read_lod_tensor(f):
    """Read one LoDTensor stream from a file object -> (ndarray, lod)."""
    version, = struct.unpack('<I', f.read(4))
    if version != 0:
        raise ValueError('unsupported LoDTensor version %d' % version)
    n_levels, = struct.unpack('<Q', f.read(8))
    lod = []
    for _ in range(n_levels):
        nbytes, = struct.unpack('<Q', f.read(8))
        lod.append(np.frombuffer(f.read(nbytes), np.uint64).tolist())
    t_version, = struct.unpack('<I', f.read(4))
    if t_version != 0:
        raise ValueError('unsupported Tensor version %d' % t_version)
    desc_len, = struct.unpack('<i', f.read(4))
    dtype_enum, dims = _parse_tensor_desc(f.read(desc_len))
    np_dtype = np.dtype(core.convert_dtype_to_np(dtype_enum))
    count = int(np.prod(dims, dtype=np.int64)) if dims else 1
    arr = np.frombuffer(f.read(count * np_dtype.itemsize), np_dtype)
    return arr.reshape(dims), lod


def deserialize_lod_tensor(data):
    import io as _io
    return read_lod_tensor(_io.BytesIO(data))
