"""Python-side metric accumulators (reference: python/paddle/fluid/metrics.py)."""

import numpy as np

__all__ = [
    'MetricBase', 'CompositeMetric', 'Precision', 'Recall', 'Accuracy',
    'ChunkEvaluator', 'EditDistance', 'DetectionMAP', 'Auc',
]


def _is_numpy_(var):
    return isinstance(var, (np.ndarray, np.generic))


class MetricBase(object):
    def __init__(self, name=None):
        self._name = str(name) if name is not None else self.__class__.__name__

    def __str__(self):
        return self._name

    def reset(self):
        states = {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith('_')
        }
        for attr, value in states.items():
            if isinstance(value, int):
                setattr(self, attr, 0)
            elif isinstance(value, float):
                setattr(self, attr, .0)
            elif isinstance(value, (np.ndarray, np.generic)):
                setattr(self, attr, np.zeros_like(value))
            else:
                setattr(self, attr, None)

    def get_config(self):
        return {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith('_')
        }

    def update(self, preds, labels):
        raise NotImplementedError()

    def eval(self):
        raise NotImplementedError()


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super(CompositeMetric, self).__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise ValueError('metric should be MetricBase')
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision (reference metrics.py Precision)."""

    def __init__(self, name=None):
        super(Precision, self).__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype('int32').flatten()
        labels = np.asarray(labels).astype('int32').flatten()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else .0


class Recall(MetricBase):
    def __init__(self, name=None):
        super(Recall, self).__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype('int32').flatten()
        labels = np.asarray(labels).astype('int32').flatten()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else .0


class Accuracy(MetricBase):
    """Weighted accuracy accumulator fed from the accuracy op's output."""

    def __init__(self, name=None):
        super(Accuracy, self).__init__(name)
        self.value = .0
        self.weight = .0

    def update(self, value, weight):
        self.value += float(np.asarray(value).flatten()[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError('Accuracy has no data; call update first')
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """Chunk F1 from chunk_eval op outputs (reference metrics.py)."""

    def __init__(self, name=None):
        super(ChunkEvaluator, self).__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).flatten()[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).flatten()[0])
        self.num_correct_chunks += int(
            np.asarray(num_correct_chunks).flatten()[0])

    def eval(self):
        precision = float(
            self.num_correct_chunks
        ) / self.num_infer_chunks if self.num_infer_chunks else 0
        recall = float(self.num_correct_chunks
                       ) / self.num_label_chunks if self.num_label_chunks else 0
        f1_score = float(2 * precision * recall) / (
            precision + recall) if self.num_correct_chunks else 0
        return precision, recall, f1_score


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super(EditDistance, self).__init__(name)
        self.total_distance = .0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances != 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError('no data in EditDistance')
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class Auc(MetricBase):
    """Streaming AUC over confusion-bins (reference metrics.py Auc)."""

    def __init__(self, name=None, curve='ROC', num_thresholds=200):
        super(Auc, self).__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self.tp_list = np.zeros((num_thresholds, ))
        self.fn_list = np.zeros((num_thresholds, ))
        self.tn_list = np.zeros((num_thresholds, ))
        self.fp_list = np.zeros((num_thresholds, ))

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).flatten()
        kepsilon = 1e-7
        thresholds = [(i + 1) * 1.0 / (self._num_thresholds - 1)
                      for i in range(self._num_thresholds - 2)]
        thresholds = [0.0 - kepsilon] + thresholds + [1.0 + kepsilon]
        pos_prob = preds[:, -1] if preds.ndim > 1 else preds
        for i, thresh in enumerate(thresholds):
            pred_pos = pos_prob >= thresh
            self.tp_list[i] += np.sum(pred_pos & (labels > 0))
            self.fp_list[i] += np.sum(pred_pos & (labels <= 0))
            self.fn_list[i] += np.sum(~pred_pos & (labels > 0))
            self.tn_list[i] += np.sum(~pred_pos & (labels <= 0))

    def eval(self):
        epsilon = 1e-6
        num_thresholds = self._num_thresholds
        tpr = (self.tp_list.astype('float64') + epsilon) / (
            self.tp_list + self.fn_list + epsilon)
        fpr = self.fp_list.astype('float64') / (
            self.fp_list + self.tn_list + epsilon)
        rec = (self.tp_list.astype('float64') + epsilon) / (
            self.tp_list + self.fp_list + epsilon)
        x = fpr[::-1] if self._curve == 'ROC' else rec[::-1]
        y = tpr[::-1]
        auc_value = 0.0
        for i in range(num_thresholds - 1):
            auc_value += (x[i + 1] - x[i]) * (y[i + 1] + y[i]) / 2.0
        return abs(auc_value)


class DetectionMAP(MetricBase):
    def __init__(self, name=None):
        super(DetectionMAP, self).__init__(name)
        self.has_state = None

    def update(self, value, weight=1):
        if not _is_numpy_(np.asarray(value)):
            raise ValueError('value must be numpy-compatible')
        self.value = np.asarray(value)
        self.weight = weight
        self.has_state = True

    def eval(self):
        if self.has_state is None:
            raise ValueError('DetectionMAP has no accumulated state')
        return float(np.asarray(self.value).flatten()[0])
