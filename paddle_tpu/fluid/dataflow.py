"""Overlapped input pipeline: reader-fed multi-step dispatch with
double-buffered device staging.

The reference Fluid stack overlaps host decode/transfer with device
compute through py_reader + double_buffer (executor.cc:321-339 pulls
fresh data every iteration; create_double_buffer_reader_op.cc stages the
next batch ahead).  Our multi-step scan path (`run_multi`) removed the
per-step dispatch tax but left feed preparation ON the dispatch critical
path: every K-step block was stacked and `device_put` synchronously
before the dispatch could issue.

`FeedPipeline` retires that tax:

  1. a background STAGING thread drains K fresh minibatches per block
     from a py_reader feeder (or any iterator of feed dicts), prepares
     them (LoD -> padded + @SEQLEN), stacks them into ONE scanned
     [K, ...] block, and places it on device — plain `device_put` for
     `Executor`, dp-sharded placement via the compiled block's
     `scanned_sharding` (parallel.scanned_spec) for `ParallelExecutor`;
  2. the DISPATCH loop issues each staged block through the executor's
     async front half (`_dispatch_multi_scanned` — no host sync), so
     while dispatch N computes on device, block N+1 is already being
     staged and block N-1's fetches are being delivered;
  3. a bounded ``pipeline_depth`` of dispatches stays in flight (2 =
     double buffering); the scanned block is DONATED on device so two
     in-flight dispatches recycle the feed buffer instead of holding
     2x K batches alive;
  4. feed-stall seconds, overlap ratio and queue depth surface through
     the `fluid.profiler` metrics-source registry (and ``pipeline/``
     timeline spans, rendered by tools/timeline.py in a ``:pipeline``
     row).

`run_multi(reader=..., steps=K)` is the synchronous one-dispatch form:
it drains K DISTINCT batches from the reader (matching the reference
per-iteration pull) and trains on them as one scanned dispatch — scope
state lands exactly as K sequential run() calls over the same batch
stream would leave it.
"""

import collections
import threading
import time
import queue as _queue

from . import core
from . import profiler as _profiler
from . import trace as _trace
from .executor import (prepare_feed_arrays, feed_signature, stack_steps,
                       _current_scope)
from .framework import default_main_program, Variable

__all__ = ['FeedPipeline', 'FeedPipelineError', 'drain_reader_feed_list']


class FeedPipelineError(RuntimeError):
    """A FeedPipeline staging-thread failure (the source reader or the
    stager itself raised).  Raised at most ONCE per pipeline — by the
    iteration loop when it hits the EOF sentinel, or by ``close()`` for
    an error that raced the close and was never delivered — with the
    original exception as ``__cause__``."""


def check_reader_args(what, feed, feed_list, steps=None,
                      require_steps=False):
    """Shared reader-mode argument validation for the four reader-fed
    multi paths (Executor/ParallelExecutor × run_multi/run_eval_multi):
    reader= is exclusive with feed=/feed_list=, and the EVAL paths
    (require_steps) have no default step count — a drain-contract
    change must not leave the four sites validating differently."""
    if feed is not None or feed_list is not None:
        raise ValueError('%s: pass reader= OR feed/feed_list' % what)
    if require_steps and (steps is None or int(steps) < 1):
        raise ValueError('%s: reader= needs steps >= 1, got %r'
                         % (what, steps))

_PIPELINE_SEQ = [0]
_PIPELINE_SEQ_LOCK = threading.Lock()

# most recent dispatches kept in FeedPipeline.dispatch_log — far above
# any contract test's horizon, bounded for open-ended pipelines
_DISPATCH_LOG_CAP = 4096


def find_read_op(program, reader=None):
    """The program's read op (optionally the one consuming ``reader``).
    Reader-driven run_multi composes with exactly ONE reader: a program
    pulling from several queues has no single batch stream to contract
    against K sequential run() calls."""
    ops = [op for op in program.global_block().ops if op.type == 'read']
    if reader is not None:
        name = reader.name if isinstance(reader, Variable) else str(reader)
        ops = [op for op in ops if op.input('Reader')[0] == name]
        if not ops:
            raise RuntimeError(
                'run_multi(reader=...): the program has no read op '
                'consuming reader %r' % name)
    if not ops:
        raise RuntimeError(
            'run_multi(reader=...): the program is not reader-fed — '
            'pass feed= or feed_list= instead')
    if len(ops) > 1:
        raise RuntimeError(
            'run_multi(reader=...): the program reads from %d readers; '
            'reader-driven multi-step dispatch supports exactly one'
            % len(ops))
    return ops[0]


def _feeder_of(program, reader, place=None):
    """(feeder, output names) for the program's read op; binds the
    prefetch target to the consuming executor like run()'s pop path."""
    from .layers import io as layers_io
    op = find_read_op(program, reader)
    reader_name = op.input('Reader')[0]
    feeder = layers_io.get_reader_feeder(reader_name)
    if feeder is None:
        raise RuntimeError('no py_reader registered for %r' % reader_name)
    if place is not None:
        feeder._executor_place = place
    return feeder, list(op.output('Out'))


def drain_reader_feed_list(program, reader, steps, place=None):
    """Pop up to ``steps`` FRESH minibatches from the program's reader
    queue, as a run_multi-shaped feed_list of PREPARED feed dicts (the
    reference multi-iteration loop pulls fresh data every iteration,
    executor.cc:321-339).  The drain stops at a shape-bucket boundary —
    a ragged drop_last=False tail batch is PUSHED BACK onto the stream
    for the next call instead of crashing the scan's uniformity check
    (and losing the drained prefix).  A stream ending mid-block returns
    the shorter tail; an already-exhausted reader raises
    core.EOFException exactly like run()."""
    # NOTE twin of FeedPipeline._next_block's drain loop — same
    # pop/prepare/bucket-boundary contract, feeder.push_back as the
    # leftover mechanism (the next CALL re-drains the same feeder) and
    # pre-pad grouping (padding happens downstream in PE.run_multi's
    # feed_list normalize).  A boundary-semantics change must land in
    # BOTH.
    feeder, names = _feeder_of(program, reader, place)
    out, sig0 = [], None
    for _ in range(int(steps)):
        batch = feeder.pop()
        if batch is None:
            break
        prepared = prepare_feed_arrays(dict(zip(names, batch)))
        sig = feed_signature(prepared)
        if out and sig != sig0:
            feeder.push_back(batch)
            break
        sig0 = sig
        out.append(prepared)
    if not out:
        raise core.EOFException(
            'reader is exhausted — call reader.reset() and '
            'reader.start() for the next pass')
    return out


class _Block(object):
    """One staged K-step scan block."""

    __slots__ = ('steps', 'sig_feed', 'scanned', 'placed', 'real',
                 'padded', 'batch_feed_names', 'indices', 'exchanges')

    def __init__(self, steps, sig_feed, scanned, placed, real=0, padded=0,
                 batch_feed_names=None, indices=None):
        self.steps = steps
        self.sig_feed = sig_feed  # per_step[0]: keys the compile cache
        self.scanned = scanned  # {name: [K, ...]}
        self.placed = placed
        # the LAST step's real/padded row counts (fetches come from the
        # last iteration): batch-led fetches of a dp-padded lot trim
        # back to the real rows, like PE.run_multi's
        self.real = real
        self.padded = padded
        # pre-pad provenance from the padding pass: which feeds are
        # batch-led, so an aux feed whose rows merely coincide with the
        # padded lot size is never masked or trimmed (PR 1 contract)
        self.batch_feed_names = batch_feed_names
        # source ordinals of the drained batches this block carries —
        # the bucketed variant reorders across buckets, and
        # ``FeedPipeline.dispatch_log`` makes the realized training
        # order observable (and contract-testable)
        self.indices = indices
        # (cache, exchange) pairs staged by the prefetch hook (ISSUE
        # 12): the dispatch loop applies them right before this block's
        # dispatch — the host fetch they started OVERLAPS the previous
        # dispatch's device compute
        self.exchanges = ()


class FeedPipeline(object):
    """Reader-fed multi-step training with double-buffered device
    staging: block N+1 stages on a background thread while dispatch N
    computes; up to ``pipeline_depth`` dispatches stay in flight.

    executor: `fluid.Executor` or `fluid.ParallelExecutor`.
    fetch_list: fetch targets (the LAST step of each dispatch delivers).
    reader: a py_reader Variable the program consumes via read_file, OR
    source: any iterator of feed dicts (the Trainer's DataFeeder form).
    steps: minibatches per dispatch (the scan length K).
    pipeline_depth: staged blocks ahead + dispatches in flight (2 =
        double buffering).
    bucketed: route each drained batch to its shape-bucket's OPEN
        block instead of closing a block at every bucket boundary
        (ISSUE 5) — one scan executable per (batch, trailing) bucket,
        so a length-skewed reader pipelines full K-step blocks without
        an upstream bucketing pass.  Batches stay in reader order
        WITHIN a bucket; dispatches issue in bucket-completion order,
        recorded per dispatch in ``dispatch_log`` (source ordinals).
    max_open_buckets: bound on concurrently accumulating buckets; the
        least-recently-fed one flushes early as a shorter block beyond
        it (the boundary push-back generalized to bounded memory).
    watchdog_stall_s: feed-stall threshold (seconds) for the trace
        watchdog (ISSUE 6) — a started pipeline registers a probe over
        how long the dispatch loop has currently been blocked on the
        staging queue; crossing it dumps the flight recorder.  None
        (default) registers no probe.  With ``embed_caches`` set, the
        same threshold also arms a prefetch-stall probe per cache
        (how long the dispatch loop has been waiting on a late host
        row fetch).
    embed_caches: two-tier embedding stores (ISSUE 12,
        ``distributed.CachedEmbeddingTable``) — the STAGING thread
        remaps each block's id feeds to slab slots and starts the
        block's host row exchange (miss fetch + dirty-eviction
        writeback) while the PREVIOUS dispatch still computes; the
        dispatch loop applies the exchange just before the block
        dispatches.  A fetch that has not landed in time is a counted
        ``prefetch_stall``, never a correctness hazard.

    Iterate the pipeline to drive it: each item is one dispatch's
    converted last-step fetches.  ``metrics()`` snapshots feed-stall
    seconds, overlap ratio and queue depth; inside a profiler window the
    same snapshot rides the ``.events.json`` sidecar and ``pipeline/``
    spans land in the timeline (`tools/timeline.py` renders them in a
    ``:pipeline`` row)."""

    def __init__(self, executor, fetch_list, program=None, reader=None,
                 source=None, steps=1, pipeline_depth=2, scope=None,
                 return_numpy=True, name=None, bucketed=False,
                 max_open_buckets=4, watchdog_stall_s=None,
                 embed_caches=None, on_delivered=None):
        if (reader is None) == (source is None):
            raise ValueError('FeedPipeline: pass reader= OR source=')
        if int(steps) < 1:
            raise ValueError('FeedPipeline: steps must be >= 1')
        if int(pipeline_depth) < 1:
            raise ValueError('FeedPipeline: pipeline_depth must be >= 1')
        if int(max_open_buckets) < 1:
            raise ValueError('FeedPipeline: max_open_buckets must be >= 1')
        self._exe = executor
        self._is_spmd = hasattr(executor, '_mesh')
        if self._is_spmd:
            if program is not None or scope is not None:
                raise ValueError(
                    'FeedPipeline: a ParallelExecutor runs its OWN '
                    'main_program in its own scope — drop program=/'
                    'scope=, or build the ParallelExecutor over them')
            self._program = executor._main_program
            # lots whose batch is not divisible by the dp extent pad
            # with masked samples on the staging thread (the PR 1
            # machinery), exactly like PE.run_multi's explicit lots
            self._pad = executor._pad_ragged
        else:
            self._program = (program if program is not None
                             else default_main_program())
            self._scope = scope if scope is not None else _current_scope()
        self._fetch_list = fetch_list
        self.steps = int(steps)
        self.pipeline_depth = int(pipeline_depth)
        self._return_numpy = return_numpy
        if reader is not None:
            place = None if self._is_spmd else self._exe.place
            feeder, names = _feeder_of(self._program, reader, place)
            self._next_batch = self._reader_batches(feeder, names)
        else:
            self._next_batch = iter(source)
        self._staged = _queue.Queue(maxsize=self.pipeline_depth)
        self._inflight = []
        self._pending = None  # a prepared batch held across a bucket split
        self._embed_caches = list(embed_caches or [])
        run_scope = (executor._scope if self._is_spmd else self._scope)
        for cache in self._embed_caches:
            cache.check_scope(run_scope, 'FeedPipeline')
        # bucketed variant (ISSUE 5): instead of CLOSING a block at a
        # shape-bucket boundary, route each drained batch to its
        # bucket's open block — one scan executable per (batch,
        # trailing) bucket — so a length-skewed reader pipelines
        # without an upstream bucketing pass.  ``_open`` maps feed
        # signature -> the bucket's accumulating per-step list; at most
        # ``max_open_buckets`` stay open (the LRU one flushes early as
        # a shorter block — the bucket-boundary push-back generalized:
        # bounded staging memory instead of a pushed-back tail).
        self.bucketed = bool(bucketed)
        self.max_open_buckets = int(max_open_buckets)
        self._open = collections.OrderedDict()
        self._drained = 0  # source ordinal of the next drained batch
        # realized training order (bucketed mode only): one list of
        # source ordinals per dispatch, appended when the dispatch
        # issues — non-bucketed dispatches stay in reader order, so
        # nothing is recorded there.  Bounded: an open-ended source=
        # pipeline keeps only the most recent window instead of
        # growing forever
        self.dispatch_log = collections.deque(maxlen=_DISPATCH_LOG_CAP)
        # delivery hook (ISSUE 13): called AFTER a dispatch's fetches
        # convert (i.e. the dispatch has synced) with the dispatch's
        # source ordinals and converted fetches — the elastic job's
        # ack-after-sync point (a task is reported finished only once
        # the dispatch that trained on it has completed on device)
        self._on_delivered = on_delivered
        self._placer = None  # set before the first placed block
        self._error = None
        self._error_delivered = False
        self._closed = False
        self._thread = None
        self._started = False
        # trace watchdog (ISSUE 6): a feed-stall probe over how long
        # the dispatch loop has CURRENTLY been waiting on the staging
        # queue — a stall crossing the threshold dumps the flight
        # recorder (what the stager and the executors had in flight)
        self.watchdog_stall_s = (float(watchdog_stall_s)
                                 if watchdog_stall_s is not None else None)
        self._watchdog_probe = None
        self._watchdog_age_fn = None
        self._waiting_since = None
        # metrics: the staging thread owns stage_*, the dispatch loop
        # owns the rest — disjoint keys, snapshot() copies
        self._m = {'blocks_staged': 0, 'stage_s': 0.0, 'stage_s_first': 0.0,
                   'dispatches': 0, 'steps_dispatched': 0,
                   'feed_stall_s': 0.0, 'partial_blocks': 0, 'eof': False,
                   'bucket_early_flushes': 0}
        with _PIPELINE_SEQ_LOCK:
            _PIPELINE_SEQ[0] += 1
            seq = _PIPELINE_SEQ[0]
        self.name = name or ('feed-pipeline-%d' % seq)
        # sidecar metrics source, weakly bound like the serving engine's
        # so a profiled window dumps the snapshot without keeping dead
        # pipelines alive
        import weakref
        ref = weakref.ref(self)
        self._metrics_fn = lambda: (ref().metrics() if ref() else None)
        self._metrics_key = _profiler.register_metrics_source(
            self.name, self._metrics_fn)
        weakref.finalize(self, _profiler.unregister_metrics_source,
                         self._metrics_key, self._metrics_fn)

    # ---- sources -------------------------------------------------------

    @staticmethod
    def _reader_batches(feeder, names):
        while True:
            batch = feeder.pop()
            if batch is None:
                return
            yield dict(zip(names, batch))

    # ---- staging thread ------------------------------------------------

    def _put(self, item):
        while not self._closed:
            try:
                self._staged.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    def _next_block(self):
        # NOTE twin of drain_reader_feed_list's drain loop — same
        # pop/prepare/bucket-boundary contract, different leftover
        # mechanism (self._pending here vs feeder.push_back there,
        # because a plain `source=` iterator has nothing to push back
        # to) and post-pad grouping here (the sync path's padding
        # happens downstream in PE.run_multi's feed_list normalize).
        # A boundary-semantics change must land in BOTH.
        per_step, sig0, last_rp, bn0, indices = [], None, (0, 0), None, []
        while len(per_step) < self.steps:
            if self._closed:
                # close() mid-drain: stop consuming the source — a
                # zombie stager finishing its K-batch block would
                # silently eat up to `steps` more reader batches from
                # a pass the user may keep reading manually
                return None
            if self._pending is not None:
                (prepared, rp, bn, idx), self._pending = \
                    self._pending, None
            else:
                drained = self._drain_prepared()
                if drained is None:
                    break
                prepared, rp, bn, idx = drained
            sig = feed_signature(prepared)
            if per_step and sig != sig0:
                # shape-bucket boundary (e.g. a ragged FINAL batch,
                # drop_last=False): close this block and start the next
                # one at the new signature — a shorter tail block is
                # one extra (steps, shape) compile, never a crash
                self._pending = (prepared, rp, bn, idx)
                break
            sig0 = sig
            if not per_step:
                bn0 = bn  # the block's compile records step 0's view
            per_step.append(prepared)
            indices.append(idx)
            last_rp = rp
        if not per_step:
            return None
        return self._finish_block(per_step, last_rp, bn0, indices)

    def _drain_prepared(self):
        """Pop + prepare (+ dp-pad under SPMD) ONE source batch; None at
        EOF.  Returns (prepared, (real, padded), batch_names, ordinal)."""
        try:
            batch = next(self._next_batch)
        except StopIteration:
            return None
        prepared = prepare_feed_arrays(dict(batch))
        rp, bn = (0, 0), None
        if self._is_spmd:
            # dp-pad ragged lots (masked samples) BEFORE the bucket
            # grouping, so a non-divisible tail becomes its own padded
            # block instead of failing the sharded device_put on the
            # staging thread; the report records pre-pad batch-led
            # provenance
            rpt = {}
            prepared, real, padded = self._pad(prepared, report=rpt)
            rp, bn = (real, padded), rpt.get('batch_names')
        idx = self._drained
        self._drained += 1
        return prepared, rp, bn, idx

    def _finish_block(self, per_step, last_rp, bn0, indices):
        # the prefetch hook (ISSUE 12): remap each cache's id feeds to
        # slab slots and START the block's host row exchange HERE, on
        # the staging thread — the master-table fetch runs while the
        # previous dispatch computes on device
        exchanges = [(cache, cache.stage_feed_list(per_step,
                                                   steps=len(per_step)))
                     for cache in self._embed_caches]
        # uniformity holds by construction: every step shares one sig
        stacked = {n: stack_steps([fa[n] for fa in per_step])
                   for n in per_step[0]}
        placer = self._placer
        if placer is not None:
            stacked = {n: placer(n, v) for n, v in stacked.items()}
        block = _Block(len(per_step), per_step[0], stacked,
                       placer is not None, last_rp[0], last_rp[1], bn0,
                       indices)
        block.exchanges = exchanges
        return block

    def _pop_open(self, last=False):
        """Flush one open bucket as a (possibly shorter) block — always
        the least-recently-FED one (appends move_to_end, so the front
        of ``_open`` is the stalest bucket), both under the
        max_open_buckets bound and when EOF drains the partials."""
        _, entry = self._open.popitem(last=last)
        per_step, last_rp, bn0, indices = entry
        return self._finish_block(per_step, last_rp, bn0, indices)

    def _next_block_bucketed(self):
        """The bucketed drain (ISSUE 5): route each drained batch to
        its feed-signature bucket's OPEN block; a bucket reaching
        ``steps`` emits.  More than ``max_open_buckets`` distinct
        shapes in flight flush the least-recently-fed bucket early as
        a shorter block (bounded staging memory — the generalization
        of the non-bucketed path's boundary push-back); EOF flushes
        the remaining partials in least-recently-fed order.  Interleaved
        shape-skewed readers thus pipeline full K-step blocks — one
        scan executable per (batch, trailing) bucket — instead of
        fragmenting into 1-step blocks at every boundary."""
        while True:
            if self._closed:
                return None
            drained = self._drain_prepared()
            if drained is None:
                break
            prepared, rp, bn, idx = drained
            sig = feed_signature(prepared)
            entry = self._open.get(sig)
            if entry is None:
                entry = self._open[sig] = [[], (0, 0), bn, []]
            entry[0].append(prepared)
            entry[1] = rp
            entry[3].append(idx)
            self._open.move_to_end(sig)
            if len(entry[0]) >= self.steps:
                del self._open[sig]
                return self._finish_block(*entry)
            if len(self._open) > self.max_open_buckets:
                self._m['bucket_early_flushes'] += 1
                return self._pop_open(last=False)
        if self._open:
            return self._pop_open(last=False)
        return None

    def _stage_loop(self):
        first = True
        try:
            while not self._closed:
                t0 = time.time()
                block = (self._next_block_bucketed() if self.bucketed
                         else self._next_block())
                if block is None:
                    self._m['eof'] = True
                    break
                dt = time.time() - t0
                self._m['blocks_staged'] += 1
                self._m['stage_s'] += dt
                if first:
                    self._m['stage_s_first'] = dt
                    first = False
                if block.steps < self.steps:
                    self._m['partial_blocks'] += 1
                _profiler.record_event('pipeline/stage[x%d]' % block.steps,
                                       dt, start=t0)
                if not self._put(block):
                    return
        except BaseException as e:
            self._error = e
        finally:
            self._put(None)

    # ---- dispatch loop -------------------------------------------------

    def _feed_stall_age(self):
        """Seconds the dispatch loop has been blocked on the staging
        queue RIGHT NOW (None when it is not waiting) — the watchdog's
        feed-stall probe."""
        since = self._waiting_since
        return (time.time() - since) if since is not None else None

    def start(self):
        if self._closed:
            raise RuntimeError('FeedPipeline is closed')
        if not self._started:
            self._started = True
            self._thread = threading.Thread(
                target=self._stage_loop, name=self.name, daemon=True)
            self._thread.start()
            if self.watchdog_stall_s is not None and \
                    self._watchdog_probe is None:
                # weak closure + GC finalizer, like the metrics source:
                # the global watchdog must not pin a dropped pipeline
                import weakref
                ref = weakref.ref(self)

                def age(ref=ref):
                    pipe = ref()
                    return pipe._feed_stall_age() if pipe else None

                self._watchdog_probe = _trace.watchdog.register(
                    'pipeline/%s/feed_stall' % self.name, age,
                    self.watchdog_stall_s)
                self._watchdog_age_fn = age
                weakref.finalize(self, _trace.watchdog.unregister,
                                 self._watchdog_probe, age)
                from ..distributed.embed_cache import register_stall_probe
                for cache in self._embed_caches:
                    # a late host row fetch stalls the dispatch loop the
                    # same way a slow reader does — same threshold, its
                    # own probe name (ISSUE 12)
                    register_stall_probe(
                        self,
                        'pipeline/%s/embed_cache/%s/prefetch_stall'
                        % (self.name, cache.var),
                        cache, self.watchdog_stall_s)
        return self

    def _ensure_placer(self, block):
        """Resolve the executor-specific device placement for scanned
        blocks.  `Executor` stages to its place; `ParallelExecutor`
        needs the compiled block's per-feed GSPMD sharding shifted
        right of the steps axis (`parallel.scanned_spec`), which only
        exists after the first resolve — so the FIRST block is placed
        here on the dispatch thread, and every later block is placed by
        the staging thread."""
        import jax
        if self._placer is not None:
            return
        if self._is_spmd:
            fetch_names = self._exe._fetch_names(self._fetch_list)
            compiled = self._exe._resolve(fetch_names, block.sig_feed,
                                          block.batch_feed_names)

            def placer(n, v):
                try:
                    sharding = compiled.scanned_sharding(n)
                except KeyError:
                    # a name outside the first resolve's feed set (the
                    # @SAMPLE_MASK a padded tail block adds): batch-led
                    # by construction, so the default dp spec applies
                    from jax.sharding import NamedSharding, \
                        PartitionSpec as P
                    from ..parallel.api import scanned_spec
                    spec = (P(compiled.batch_axis) if compiled.batch_axis
                            in compiled.mesh.axis_names else P())
                    sharding = NamedSharding(compiled.mesh,
                                             scanned_spec(spec))
                return jax.device_put(v, sharding)

            self._placer = placer
        else:
            dev = self._exe.place.jax_device()
            self._placer = lambda n, v, _dev=dev: jax.device_put(v, _dev)

    def _dispatch(self, block):
        # the executors add their own 'multi_dispatch' flight records;
        # this one carries the PIPELINE's view (block provenance) so a
        # stall dump shows which source batches were in flight
        _trace.flight_recorder.record(
            'pipeline_dispatch', pipeline=self.name, steps=block.steps,
            indices=list(block.indices or []),
            trace_id=getattr(_trace.current(), 'trace_id', None))
        self._ensure_placer(block)
        if not block.placed:
            block.scanned = {n: self._placer(n, v)
                             for n, v in block.scanned.items()}
            block.placed = True
        for cache, ex in block.exchanges:
            # the overlapped prefetch's device half: evicted dirty rows
            # gather out, fetched miss rows scatter in — right before
            # the dispatch that needs them (late fetch = counted stall)
            cache.apply(ex)
        if self._is_spmd:
            fetches, compiled = self._exe._dispatch_multi_scanned(
                self._fetch_list, block.sig_feed, block.scanned,
                block.steps, batch_feed_names=block.batch_feed_names)
        else:
            fetches, compiled = self._exe._dispatch_multi_scanned(
                self._program, self._fetch_list, self._scope,
                block.sig_feed, block.scanned, block.steps)
        self._m['dispatches'] += 1
        self._m['steps_dispatched'] += block.steps
        if self.bucketed:
            # only the bucketed variant reorders across buckets; the
            # sequential path's order is trivial, and an open-ended
            # source= pipeline must not grow a log it never reads
            self.dispatch_log.append(list(block.indices or []))
        self._inflight.append((fetches, compiled, block, time.time()))

    def _drain_one(self):
        fetches, compiled, block, t0 = self._inflight.pop(0)
        if self._is_spmd:
            # batch-led fetches of a dp-padded tail lot trim back to
            # the real row count, exactly like PE.run_multi's
            out = self._exe._convert_fetches(
                fetches, self._return_numpy, block.real, block.padded,
                compiled=compiled)
        else:
            out = self._exe._convert_fetches(fetches, self._return_numpy)
        _profiler.record_event('pipeline/dispatch[x%d]' % block.steps,
                               time.time() - t0, start=t0)
        if self._on_delivered is not None:
            self._on_delivered(list(block.indices or []), out)
        return out

    def __iter__(self):
        self.start()
        try:
            while True:
                t0 = time.time()
                if self._m['dispatches'] > 0:
                    # the FIRST get is warmup (nothing to overlap with
                    # yet) — the probe must match the feed_stall metric
                    # semantics below, or a slow-staging first block
                    # dumps a spurious 'stall' during normal warmup
                    self._waiting_since = t0
                try:
                    block = self._staged.get()
                finally:
                    self._waiting_since = None
                stall = time.time() - t0
                if block is None:
                    # the EOF sentinel's wait delayed no dispatch — it
                    # must not count as feed stall (it would skew the
                    # 'feed_stall ~ 0' acceptance metric)
                    self._raise_stage_error()
                    break
                if self._m['dispatches'] > 0:
                    # the FIRST get always waits (nothing to overlap
                    # with yet); only post-warmup waits are feed stall
                    self._m['feed_stall_s'] += stall
                    if stall > 1e-4:
                        _profiler.record_event('pipeline/feed_stall',
                                               stall, start=t0)
                self._dispatch(block)
                while len(self._inflight) >= self.pipeline_depth:
                    yield self._drain_one()
            while self._inflight:
                yield self._drain_one()
        finally:
            # quiet close: the sentinel path above already raised any
            # stage error into the consumer; an ABANDONED iterator
            # (break / GC teardown) must not raise from a generator
            # finally — that masks the primary exception or surfaces
            # as an ignored-exception warning at GC.  An explicit
            # pipe.close() by the owner still raises (the close-race
            # contract).
            self._close_quiet()

    def run(self):
        """Drive the pipeline to EOF; returns the per-dispatch list of
        converted last-step fetches."""
        return list(self)

    def metrics(self):
        m = dict(self._m)
        m['queue_depth'] = self._staged.qsize()
        m['inflight'] = len(self._inflight)
        m['pipeline_depth'] = self.pipeline_depth
        m['steps_per_dispatch'] = self.steps
        m['bucketed'] = self.bucketed
        m['open_buckets'] = len(self._open)
        # staging hidden behind compute: of the staging seconds spent
        # AFTER the first dispatch could run, the fraction the dispatch
        # loop did NOT wait for (feed_stall ~ 0 => ratio ~ 1)
        denom = m['stage_s'] - m['stage_s_first']
        if denom > 0:
            m['overlap_ratio'] = max(0.0, min(
                1.0, (denom - m['feed_stall_s']) / denom))
        else:
            m['overlap_ratio'] = 1.0 if m['feed_stall_s'] < 1e-3 else 0.0
        if self._embed_caches:
            m['embed_cache'] = {c.var: c.metrics()
                                for c in self._embed_caches}
        return m

    def _drain_staged(self):
        try:
            while True:
                self._staged.get_nowait()
        except _queue.Empty:
            pass

    def _raise_stage_error(self):
        """Surface a staging-thread failure exactly ONCE as the typed
        FeedPipelineError (ISSUE 13 satellite): the iteration loop
        raises it when the EOF sentinel lands; an error that races
        close() — the stager crashing while the pipeline shuts down —
        is raised by close() instead, and a second close() (or the
        iterator's finally re-entering close) never re-raises."""
        if self._error is None or self._error_delivered:
            return
        self._error_delivered = True
        err = self._error
        raise FeedPipelineError(
            'FeedPipeline source failed: %r' % (err, )) from err

    def close(self):
        if self._closed:
            return
        self._closed = True
        # unblock a stager stuck on a full queue...
        self._drain_staged()
        if self._thread is not None:
            # bounded join: _closed is set, so the stager's put() loop
            # exits and _next_block stops consuming — a stage-thread
            # exception during this window is captured, not a hang
            self._thread.join(timeout=5)
            self._thread = None
        # ...and drop the block its unblocked put() may have deposited
        # AFTER the first drain — a staged ResNet-scale device block
        # pinned in the queue would hold HBM for as long as the caller
        # keeps the pipeline object (e.g. to read metrics())
        self._drain_staged()
        self._inflight = []
        if self._watchdog_probe is not None:
            _trace.watchdog.unregister(self._watchdog_probe,
                                       self._watchdog_age_fn)
            self._watchdog_probe = None
        _profiler.unregister_metrics_source(self._metrics_key,
                                            self._metrics_fn)
        # a racing stage-thread error nobody iterated into: surface it
        # here, once, AFTER the pipeline is fully torn down (resources
        # above are released whether or not this raises)
        self._raise_stage_error()

    def _close_quiet(self):
        """close() with a racing stage error recorded but not raised —
        for paths where raising would mask a primary exception (the
        error is still marked delivered, so no later close re-raises
        a half-reported failure)."""
        try:
            self.close()
        except FeedPipelineError:
            pass

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            # a primary exception is propagating: never mask it with
            # the close-race error
            self._close_quiet()
        else:
            self.close()
