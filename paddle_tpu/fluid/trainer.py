"""High-level Trainer / event loop (reference: python/paddle/fluid/trainer.py).

Same event-driven surface as the reference (BeginEpochEvent/EndStepEvent
callbacks, trainer.py:40-83), checkpointing via CheckpointConfig
(trainer.py:100), automatic resume from the newest checkpoint.  Distributed
training maps to SPMD (ParallelExecutor) instead of the transpiled pserver
path.

Checkpointing rides the elastic subsystem's manifest store (ISSUE 13):
``distributed.elastic.AsyncShardedCheckpoint`` — per-var shard files,
atomic manifest commit, bounded retention, and the WRITE on a background
thread so the event loop never blocks on checkpoint IO.  Pre-manifest
checkpoints (the old ``<dir>/<serial>/`` layout) still resume.
"""

import os

from . import core
from .framework import Program, program_guard, default_main_program, \
    default_startup_program
from .executor import Executor, scope_guard
from . import io as fluid_io
from .data_feeder import DataFeeder

__all__ = [
    'Trainer', 'BeginEpochEvent', 'EndEpochEvent', 'BeginStepEvent',
    'EndStepEvent', 'CheckpointConfig',
]


class BeginEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent(object):
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig(object):
    """(reference trainer.py:100)"""

    def __init__(self,
                 checkpoint_dir=None,
                 max_num_checkpoints=3,
                 epoch_interval=1,
                 step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            os.getcwd(), 'checkpoints')
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(epoch_interval, 1)
        self.step_interval = max(step_interval, 1)
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial = None


def _serial_dir(checkpoint_dir, serial):
    return os.path.join(checkpoint_dir, str(serial))


def _latest_serial(checkpoint_dir):
    if not os.path.isdir(checkpoint_dir):
        return None
    serials = [int(d) for d in os.listdir(checkpoint_dir) if d.isdigit()]
    return max(serials) if serials else None


class Trainer(object):
    """(reference trainer.py:169)

    train_func must return [loss] (optionally [loss, *metrics])."""

    def __init__(self,
                 train_func,
                 optimizer_func,
                 param_path=None,
                 place=None,
                 parallel=False,
                 checkpoint_config=None):
        self.__stop = False
        self.parallel = parallel
        self.place = place if place is not None else core.CPUPlace()
        self.checkpoint_cfg = checkpoint_config
        if self.checkpoint_cfg is not None and not isinstance(
                self.checkpoint_cfg, CheckpointConfig):
            raise TypeError('checkpoint_config must be CheckpointConfig')

        self.scope = core.Scope()
        self.startup_program = Program()
        self.train_program = Program()

        with program_guard(self.train_program, self.startup_program):
            program_func_outs = train_func()
            self.train_func_outputs = program_func_outs if isinstance(
                program_func_outs, list) else [program_func_outs]
            self.test_program = self.train_program.clone(for_test=True)
            optimizer = optimizer_func()
            loss = self.train_func_outputs[0]
            optimizer.minimize(loss)

        self.exe = Executor(self.place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)

        if param_path and os.path.isdir(param_path):
            with scope_guard(self.scope):
                fluid_io.load_persistables(
                    self.exe, dirname=param_path,
                    main_program=self.startup_program)

        self._ckpt_store = None
        if self.checkpoint_cfg is not None:
            from ..distributed.elastic import AsyncShardedCheckpoint
            cfg = self.checkpoint_cfg
            self._ckpt_store = AsyncShardedCheckpoint(
                cfg.checkpoint_dir, keep=cfg.max_num_checkpoints)
            manifest = self._ckpt_store.latest()
            if manifest is not None:
                serial, arrays, extras = self._ckpt_store.load(manifest)
                cfg.load_serial = serial
                # informational only (the reference surface exposes
                # them): the event loops do NOT fast-forward past
                # already-trained epochs/steps — resumed state is the
                # PARAMS; the data position is the caller's reader
                cfg.epoch_id = int(extras.get('epoch', 0))
                cfg.step_id = int(extras.get('step', 0))
                with scope_guard(self.scope):
                    for name, arr in arrays.items():
                        self.scope.var(name).set_value(arr)
            else:
                # pre-manifest checkpoint (the old <dir>/<serial>/
                # per-var layout): still resumes
                serial = _latest_serial(cfg.checkpoint_dir)
                if serial is not None:
                    cfg.load_serial = serial
                    with scope_guard(self.scope):
                        fluid_io.load_persistables(
                            self.exe,
                            _serial_dir(cfg.checkpoint_dir, serial),
                            main_program=self.train_program)

    def stop(self):
        self.__stop = True

    def train(self, num_epochs, event_handler, reader=None, feed_order=None,
              steps_per_dispatch=1, pipeline_depth=2):
        """Run the event loop.  With ``steps_per_dispatch > 1`` the loop
        rides the overlapped input pipeline (fluid.dataflow.FeedPipeline):
        K reader batches train as ONE multi-step device dispatch while
        the NEXT block stages on a background thread — the reference's
        py_reader + double_buffer overlap, at scan-block granularity.
        Step events then fire per DISPATCH and are POST-HOC delivery
        callbacks: by the time BeginStepEvent/EndStepEvent fire, that
        dispatch has already executed (and the next may be in flight),
        so a handler cannot steer the step it names —
        ``fetch_metrics`` is ignored (metrics are the block's LAST
        step, always fetched; toggling would recompile the scanned
        executable) and ``stop()`` takes effect up to
        ``pipeline_depth`` dispatches late.  Handlers that must run
        BEFORE each step (per-step LR schedules written to the scope)
        need the plain ``steps_per_dispatch=1`` loop."""
        if int(steps_per_dispatch) > 1:
            return self._train_pipelined(
                num_epochs, event_handler, reader, feed_order,
                int(steps_per_dispatch), int(pipeline_depth))
        try:
            with scope_guard(self.scope):
                feeder = DataFeeder(
                    feed_list=feed_order, place=self.place,
                    program=self.train_program)
                for epoch_id in range(num_epochs):
                    event_handler(BeginEpochEvent(epoch_id))
                    for step_id, data in enumerate(reader()):
                        if self.__stop:
                            return
                        begin_event = BeginStepEvent(epoch_id, step_id)
                        event_handler(begin_event)
                        fetch_list = self.train_func_outputs \
                            if begin_event.fetch_metrics else []
                        metrics = self.exe.run(
                            self.train_program,
                            feed=feeder.feed(data),
                            fetch_list=fetch_list)
                        if self.checkpoint_cfg is not None:
                            self._save_checkpoint(epoch_id, step_id)
                        event_handler(
                            EndStepEvent(epoch_id, step_id, metrics))
                    event_handler(EndEpochEvent(epoch_id))
        finally:
            # the async writer must have committed before train()
            # returns — a caller reading the checkpoint dir right
            # after must see the newest manifest.  On the exception
            # path the flush goes QUIET: a writer failure must not
            # mask the primary training error.
            import sys
            self._flush_checkpoints(
                quiet=sys.exc_info()[0] is not None)

    def _train_pipelined(self, num_epochs, event_handler, reader,
                         feed_order, steps, pipeline_depth):
        """The overlapped event loop: feeder-prepared batches flow
        through a FeedPipeline per epoch; each iteration is one K-step
        dispatch whose staging overlapped the previous dispatch's
        compute."""
        from .dataflow import FeedPipeline
        try:
            with scope_guard(self.scope):
                feeder = DataFeeder(
                    feed_list=feed_order, place=self.place,
                    program=self.train_program)
                for epoch_id in range(num_epochs):
                    event_handler(BeginEpochEvent(epoch_id))
                    pipe = FeedPipeline(
                        self.exe, fetch_list=self.train_func_outputs,
                        program=self.train_program,
                        source=(feeder.feed(data) for data in reader()),
                        steps=steps, pipeline_depth=pipeline_depth,
                        scope=self.scope)
                    try:
                        for step_id, metrics in enumerate(pipe):
                            if self.__stop:
                                return
                            event_handler(BeginStepEvent(epoch_id,
                                                         step_id))
                            if self.checkpoint_cfg is not None:
                                self._save_checkpoint(epoch_id, step_id)
                            event_handler(
                                EndStepEvent(epoch_id, step_id, metrics))
                    finally:
                        pipe.close()
                    event_handler(EndEpochEvent(epoch_id))
        finally:
            import sys
            self._flush_checkpoints(
                quiet=sys.exc_info()[0] is not None)

    def test(self, reader, feed_order):
        with scope_guard(self.scope):
            feeder = DataFeeder(
                feed_list=feed_order, place=self.place,
                program=self.test_program)
            accumulated = [0.0] * len(self.train_func_outputs)
            count = 0
            for data in reader():
                outs = self.exe.run(
                    self.test_program,
                    feed=feeder.feed(data),
                    fetch_list=self.train_func_outputs)
                accumulated = [
                    a + float(o.flatten()[0])
                    for a, o in zip(accumulated, outs)
                ]
                count += 1
            return [a / max(count, 1) for a in accumulated]

    def save_params(self, param_path):
        with scope_guard(self.scope):
            fluid_io.save_persistables(
                self.exe, dirname=param_path,
                main_program=self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        with scope_guard(self.scope):
            target_vars = [
                self.train_func_outputs[i] for i in target_var_indexes
            ]
            fluid_io.save_inference_model(param_path, feeded_var_names,
                                          target_vars, self.exe,
                                          self.train_program)

    def _save_checkpoint(self, epoch_id, step_id):
        cfg = self.checkpoint_cfg
        if epoch_id % cfg.epoch_interval != 0 or \
                step_id % cfg.step_interval != 0:
            return
        serial = (cfg.load_serial or 0) + epoch_id * 100000 + step_id + 1
        arrays = {}
        for var in self.train_program.list_vars():
            if not fluid_io.is_persistable(var):
                continue
            sv = self.scope.find_var(var.name)
            if sv is None or sv.value() is None:
                continue
            arrays[var.name] = fluid_io._scope_value(self.scope, var.name)
        # async manifest commit + bounded retention live in the store;
        # the host copies above are the only work on the event loop
        self._ckpt_store.save(serial, arrays,
                              extras={'epoch': epoch_id, 'step': step_id})

    def _flush_checkpoints(self, quiet=False):
        """Drain the async writer so checkpoints are durable when
        train() returns.  ``quiet`` is the exception-path form: a
        checkpoint-writer failure must never mask the primary training
        exception (the FeedPipeline close-race rule)."""
        if self._ckpt_store is None:
            return
        try:
            self._ckpt_store.wait()
        except Exception:
            if not quiet:
                raise
            return
        # a pre-manifest resume leaves legacy <dir>/<serial>/ trees the
        # store's own retention never touches: once a manifest is
        # durably committed they are superseded — drop them so
        # max_num_checkpoints keeps bounding the directory again
        cfg = self.checkpoint_cfg
        if self._ckpt_store.latest() is not None:
            import shutil
            for d in os.listdir(cfg.checkpoint_dir):
                if d.isdigit():
                    shutil.rmtree(_serial_dir(cfg.checkpoint_dir, d),
                                  ignore_errors=True)
