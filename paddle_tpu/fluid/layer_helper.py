"""LayerHelper: shared plumbing for layer functions
(reference: python/paddle/fluid/layer_helper.py)."""

import copy

from . import core
from . import unique_name
from .framework import Variable, Parameter, default_main_program, \
    default_startup_program
from .param_attr import ParamAttr
from .initializer import Constant, Xavier

__all__ = ['LayerHelper']


class LayerHelper(object):
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get('name')
        if name is None:
            self.kwargs['name'] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs['name']

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    def multiple_input(self, input_param_name='input'):
        inputs = self.kwargs.get(input_param_name, [])
        if isinstance(inputs, Variable):
            return [inputs]
        return list(inputs)

    def input(self, input_param_name='input'):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError('%s layer needs exactly one input' %
                             self.layer_type)
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get('param_attr'))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get('bias_attr'))

    def multiple_param_attr(self, length):
        param_attr = self.param_attr
        if param_attr is False:
            # param_attr=False: parameter exists but is frozen (bias_attr
            # =False is handled earlier by append_bias_op skipping the op)
            param_attr = ParamAttr(trainable=False)
        if isinstance(param_attr, ParamAttr):
            param_attr = [param_attr]
        if len(param_attr) != 1 and len(param_attr) != length:
            raise ValueError('parameter number mismatch')
        elif len(param_attr) == 1 and length != 1:
            param_attr = param_attr + [
                copy.deepcopy(param_attr[0]) for _ in range(length - 1)
            ]
        return param_attr

    def iter_inputs_and_params(self, input_param_name='input'):
        inputs = self.multiple_input(input_param_name)
        param_attrs = self.multiple_param_attr(len(inputs))
        for ipt, param_attr in zip(inputs, param_attrs):
            yield ipt, param_attr

    def input_dtype(self, input_param_name='input'):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for ipt in inputs:
            if dtype is None:
                dtype = ipt.dtype
            elif dtype != ipt.dtype:
                raise ValueError('Data Type mismatch: %d to %d' %
                                 (dtype, ipt.dtype))
        return dtype

    def create_parameter(self,
                         attr,
                         shape,
                         dtype,
                         is_bias=False,
                         default_initializer=None):
        """Create a Parameter in the main program and its init op in the
        startup program (the two-program design of the reference)."""
        if attr is False:
            # layers that create their params directly (batch_norm scale/
            # bias etc.) treat attr=False as a frozen parameter
            attr = ParamAttr(trainable=False)
        attr = copy.deepcopy(attr) if attr is not None else ParamAttr()
        # explicit shared name: reuse the existing parameter (the reference
        # shares e.g. word2vec's 'shared_w' / SRL's 'crfw' this way)
        if attr.name is not None and \
                self.main_program.global_block().has_var(attr.name):
            existing = self.main_program.global_block().var(attr.name)
            if isinstance(existing, Parameter):
                if tuple(existing.shape) != tuple(shape):
                    raise ValueError(
                        'shared parameter %r shape mismatch: %s vs %s' %
                        (attr.name, existing.shape, shape))
                if core.convert_np_dtype_to_dtype_(existing.dtype) != \
                        core.convert_np_dtype_to_dtype_(dtype):
                    raise ValueError(
                        'shared parameter %r dtype mismatch: %s vs %s' %
                        (attr.name, existing.dtype, dtype))
                return existing
        if default_initializer is None:
            if is_bias:
                attr._set_default_bias_initializer()
            else:
                attr._set_default_param_initializer()
        else:
            attr._set_default_initializer(default_initializer)
        if attr.name is None:
            attr.name = unique_name.generate('.'.join(
                [self.name, 'w' if not is_bias else 'b']))

        startup_block = self.startup_program.global_block()
        startup_param = startup_block.create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs())
        if attr.initializer is not None:
            attr.initializer(startup_param, startup_block)
        elif is_bias:
            Constant(0.0)(startup_param, startup_block)
        else:
            Xavier()(startup_param, startup_block)
        main_param = self.main_program.global_block().create_parameter(
            shape=shape, dtype=dtype, **attr._to_kwargs())
        return main_param

    def get_parameter(self, name):
        param = self.main_program.global_block().var(name)
        if not isinstance(param, Parameter):
            raise ValueError('no Parameter named %s' % name)
        return param

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate('.'.join([self.name, 'tmp'])),
            dtype=dtype,
            persistable=False,
            stop_gradient=stop_gradient)

    # reference-era alias
    create_tmp_variable = create_variable_for_type_inference

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs)

    def create_or_get_global_variable(self, name, *args, **kwargs):
        block = self.main_program.global_block()
        if not block.has_var(name):
            return self.create_global_variable(name=name, *args, **kwargs)
        return block.var(name)

    def set_variable_initializer(self, var, initializer):
        startup_block = self.startup_program.global_block()
        sv = startup_block.create_var(
            name=var.name,
            shape=var.shape,
            dtype=var.dtype,
            persistable=True)
        initializer(sv, startup_block)
        return var

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        if not bias_attr:
            return input_var
        b = self.create_parameter(
            attr=bias_attr, shape=size, dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        tmp.shape = input_var.shape
        self.append_op(
            type='elementwise_add',
            inputs={'X': [input_var],
                    'Y': [b]},
            outputs={'Out': [tmp]},
            attrs={'axis': dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get('act')
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {'type': act}
        else:
            act = copy.deepcopy(act)
        act_type = act.pop('type')
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        tmp.shape = input_var.shape
        self.append_op(
            type=act_type,
            inputs={'X': [input_var]},
            outputs={'Out': [tmp]},
            attrs=act)
        return tmp

    def is_instance(self, param_name, cls):
        param = self.kwargs.get(param_name)
        if not isinstance(param, cls):
            raise TypeError('%s of %s must be %s' %
                            (param_name, self.layer_type, cls.__name__))
