"""Graph-level autodiff: append grad ops to the program.

Mirrors the reference's ``append_backward`` (python/paddle/fluid/backward.py:469):
walks ops in reverse, synthesizes one ``<type>_grad`` op per forward op
(the analog of C++ GradOpDescMakers, framework/grad_op_desc_maker.h:34),
renames duplicated gradient outputs and inserts ``sum`` accumulation ops
(_addup_repetitive_outputs_, backward.py:135), and prunes branches that do not
reach the loss (_remove_no_grad_branch_, backward.py:204 — done here by only
visiting ops whose outputs carry gradients).

Grad ops are lowered by the generic jax.vjp machinery in
paddle_tpu.ops.registry unless an explicit grad lowering exists.
"""

import collections

import numpy as np

from . import core
from . import framework
from ..ops import registry

__all__ = ['append_backward', 'calc_gradient']

GRAD = framework.GRAD_VAR_SUFFIX


def _is_float_var(block, name):
    v = block._find_var_recursive(name)
    if v is None:
        return True  # temps default to fp32
    try:
        return np.issubdtype(v.np_dtype, np.floating)
    except Exception:
        return False


def _creates_subblock(op):
    return op.type in ('while', 'conditional_block', 'recurrent')


# op types that never get grad ops, regardless of connectivity
_NO_GRAD_OP_TYPES = {'read', 'feed', 'fetch', 'while', 'print',
                     'listen_and_serv'}


def _make_grad_op_spec(block, op, grad_known, no_grad):
    """Plan one grad op: (inputs, outputs, attrs) or None."""
    if op.type in _NO_GRAD_OP_TYPES:
        # a bounded While lowers to lax.scan, which the generic vjp can
        # reverse (the analog of the reference's WhileGradOpDescMaker,
        # operators/while_op.cc bottom); unbounded While stays opaque
        if not (op.type == 'while' and op.attrs.get('max_trip_count')):
            return None
    out_grad_names = [n + GRAD for n in op.output_arg_names]
    if not any(g in grad_known for g in out_grad_names):
        return None
    inputs = {}
    for slot, names in op.inputs.items():
        inputs[slot] = list(names)
    for slot, names in op.outputs.items():
        inputs[slot] = list(names)
        inputs[slot + GRAD] = [n + GRAD for n in names]
    outputs = {}
    any_grad = False
    for slot, names in op.inputs.items():
        gnames = []
        for n in names:
            if n in no_grad or not _is_float_var(block, n):
                gnames.append('')
            else:
                gnames.append(n + GRAD)
                any_grad = True
        outputs[slot + GRAD] = gnames
    if not any_grad:
        return None
    attrs = dict(op.attrs)
    attrs[registry.FWD_IN_SLOTS_ATTR] = list(op.inputs.keys())
    attrs[registry.FWD_OUT_SLOTS_ATTR] = list(op.outputs.keys())
    return (op.type + '_grad', inputs, outputs, attrs)


def _dedup_grad_outputs(specs):
    """Rename multiply-written grad outputs and plan sum ops after the last
    contribution (reference _addup_repetitive_outputs_).

    Writes are grouped into LIVE RANGES: maximal runs of consecutive
    writes with no intervening reader of that grad name.  Only writes in
    the same run are fork contributions to rename-and-sum; a reader in
    between (e.g. a bounded While consuming its Out@GRAD before the
    snapshot assign's backward re-populates the same name for the
    pre-loop state) seals the run, and the next write is a fresh value,
    not an accumulation."""
    runs = collections.defaultdict(list)  # name -> list of runs
    open_run = {}  # name -> the currently open run (list of (idx, slot, i))
    for idx, (_, inputs, outputs, _) in enumerate(specs):
        reads = {n for names in inputs.values() for n in names if n}
        for n in reads:
            open_run.pop(n, None)  # a read seals the open run
        for slot, names in outputs.items():
            for i, n in enumerate(names):
                if not n:
                    continue
                run = open_run.get(n)
                if run is None:
                    run = []
                    runs[n].append(run)
                    open_run[n] = run
                run.append((idx, slot, i))
    sum_after = {}  # spec index -> list of (out_name, part_names)
    serial = collections.Counter()
    for name, run_list in runs.items():
        for run in run_list:
            if len(run) <= 1:
                continue
            parts = []
            for idx, slot, i in run:
                new_name = '%s@RENAME@%d' % (name, serial[name])
                serial[name] += 1
                specs[idx][2][slot][i] = new_name
                parts.append(new_name)
            last_idx = run[-1][0]
            sum_after[last_idx] = sum_after.get(last_idx, []) + [
                (name, parts)
            ]
    return specs, sum_after


def _append_grad_ops(block, fwd_ops, grad_known, no_grad, callbacks=None):
    """Append grad ops for fwd_ops (in reverse) into block.  Returns the set
    of grad var names produced."""
    specs = []
    known = set(grad_known)
    spec_src = []
    for op in reversed(fwd_ops):
        spec = _make_grad_op_spec(block, op, known, no_grad)
        if spec is None:
            continue
        specs.append([spec[0], spec[1], spec[2], spec[3]])
        spec_src.append(op)
        for names in spec[2].values():
            for n in names:
                if n:
                    known.add(n.split('@RENAME@')[0])
    specs, sum_after = _dedup_grad_outputs(specs)
    produced = set()
    for idx, (gtype, inputs, outputs, attrs) in enumerate(specs):
        gop = block.append_op(
            type=gtype, inputs=inputs, outputs=outputs, attrs=attrs)
        for names in outputs.values():
            for n in names:
                if n:
                    base = n.split('@RENAME@')[0]
                    produced.add(base)
                    _ensure_grad_var(block, n)
        if callbacks:
            for cb in callbacks:
                cb(block=block, context={'op': gop})
        for out_name, parts in sum_after.get(idx, []):
            block.append_op(
                type='sum',
                inputs={'X': parts},
                outputs={'Out': [out_name]})
            _ensure_grad_var(block, out_name)
            produced.add(out_name)
    return produced


def _ensure_grad_var(block, grad_name):
    if block.has_var(grad_name):
        return
    base = grad_name.split('@RENAME@')[0]
    fwd_name = base[:-len(GRAD)] if base.endswith(GRAD) else base
    fwd = block._find_var_recursive(fwd_name)
    block.create_var(
        name=grad_name,
        shape=fwd.shape if fwd is not None else (),
        dtype=fwd.dtype if fwd is not None else core.VarDesc.VarType.FP32,
        persistable=False)


def _collect_no_grad(program, no_grad_set):
    no_grad = set()
    if no_grad_set:
        no_grad.update(
            v.name if isinstance(v, framework.Variable) else v
            for v in no_grad_set)
    for v in program.list_vars():
        if v.stop_gradient:
            no_grad.add(v.name)
    return no_grad


def append_backward(loss,
                    parameter_list=None,
                    no_grad_set=None,
                    callbacks=None):
    """Append backward ops computing d(loss)/d(param) for every trainable
    parameter; returns [(param, grad_var)] (reference backward.py:469)."""
    program = loss.block.program
    block = program.global_block()
    no_grad = _collect_no_grad(program, no_grad_set)

    loss_grad = loss.name + GRAD
    block.append_op(
        type='fill_constant',
        inputs={},
        outputs={'Out': [loss_grad]},
        attrs={
            'shape': list(loss.shape) or [1],
            'value': 1.0,
            'dtype': loss.dtype,
            'op_role': 'backward',
        })
    _ensure_grad_var(block, loss_grad)

    # every op before the loss-grad fill we just appended is a forward op
    fwd_ops = list(block.ops[:-1])
    _append_grad_ops(block, fwd_ops, {loss_grad}, no_grad, callbacks)

    if parameter_list is not None:
        params = [
            block.var_recursive(p) if not isinstance(p, framework.Variable)
            else p for p in parameter_list
        ]
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    params_and_grads = []
    for p in params:
        gname = p.name + GRAD
        if block.has_var(gname):
            params_and_grads.append((p, block.var(gname)))
    return params_and_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of targets w.r.t. inputs (reference backward.py:685)."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if target_gradients is None:
        target_gradients = [None] * len(targets)
    program = targets[0].block.program
    block = program.global_block()
    no_grad = _collect_no_grad(program, no_grad_set)

    n_fwd = len(block.ops)
    seed = set()
    for t, tg in zip(targets, target_gradients):
        gname = t.name + GRAD
        if tg is None:
            block.append_op(
                type='fill_constant',
                inputs={},
                outputs={'Out': [gname]},
                attrs={
                    'shape': list(t.shape) or [1],
                    'value': 1.0,
                    'dtype': t.dtype
                })
        else:
            block.append_op(
                type='assign',
                inputs={'X': [tg.name]},
                outputs={'Out': [gname]})
        _ensure_grad_var(block, gname)
        seed.add(gname)

    _append_grad_ops(block, block.ops[:n_fwd], seed, no_grad)

    grads = []
    for iv in inputs:
        gname = iv.name + GRAD
        grads.append(block.var(gname) if block.has_var(gname) else None)
    return grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    return calc_gradient(targets, inputs, target_gradients, no_grad_set)
