"""Operator-overload sugar for Variable
(reference: python/paddle/fluid/layers/math_op_patch.py)."""

from .. import core
from ..framework import Variable
from ..layer_helper import LayerHelper
from .. import unique_name

__all__ = ['monkey_patch_variable']


def monkey_patch_variable():
    def unique_tmp_name():
        return unique_name.generate('tmp')

    def current_block(var):
        return var.block.program.current_block()

    def create_new_tmp_var(block, dtype):
        return block.create_var(
            name=unique_tmp_name(), dtype=dtype, persistable=False)

    def create_scalar_op(var, value, op):
        """var <op> python-scalar via the scale op."""
        block = current_block(var)
        out = create_new_tmp_var(block, var.dtype)
        out.shape = var.shape
        if op == 'add':
            attrs = {'scale': 1.0, 'bias': float(value)}
        elif op == 'radd':
            attrs = {'scale': 1.0, 'bias': float(value)}
        elif op == 'sub':
            attrs = {'scale': 1.0, 'bias': -float(value)}
        elif op == 'rsub':
            attrs = {'scale': -1.0, 'bias': float(value)}
        elif op == 'mul':
            attrs = {'scale': float(value), 'bias': 0.0}
        elif op == 'div':
            attrs = {'scale': 1.0 / float(value), 'bias': 0.0}
        else:
            raise ValueError(op)
        block.append_op(
            type='scale',
            inputs={'X': [var]},
            outputs={'Out': [out]},
            attrs=attrs)
        return out

    def binary(op_type, reverse=False):
        def impl(self, other):
            if isinstance(other, (int, float)):
                simple = {
                    'elementwise_add': 'radd' if reverse else 'add',
                    'elementwise_sub': 'rsub' if reverse else 'sub',
                    'elementwise_mul': 'mul',
                }
                if op_type in simple:
                    return create_scalar_op(self, other, simple[op_type])
                if op_type == 'elementwise_div' and not reverse:
                    return create_scalar_op(self, other, 'div')
                # fall back: materialize the scalar as a tensor
                block = current_block(self)
                const = create_new_tmp_var(block, self.dtype)
                const.shape = (1, )
                block.append_op(
                    type='fill_constant',
                    outputs={'Out': [const]},
                    attrs={
                        'shape': [1],
                        'dtype': const.dtype,
                        'value': float(other)
                    })
                other = const
            block = current_block(self)
            lhs, rhs = (other, self) if reverse else (self, other)
            out = create_new_tmp_var(
                block,
                lhs.dtype if op_type not in _CMP_OPS else
                core.VarDesc.VarType.BOOL)
            out.shape = lhs.shape
            block.append_op(
                type=op_type,
                inputs={'X': [lhs],
                        'Y': [rhs]},
                outputs={'Out': [out]},
                attrs={'axis': -1} if op_type.startswith('elementwise')
                else {})
            return out

        return impl

    _CMP_OPS = ('less_than', 'less_equal', 'greater_than', 'greater_equal',
                'equal', 'not_equal')

    def neg(self):
        return create_scalar_op(self, 0.0, 'rsub')

    Variable.__add__ = binary('elementwise_add')
    Variable.__radd__ = binary('elementwise_add', reverse=True)
    Variable.__sub__ = binary('elementwise_sub')
    Variable.__rsub__ = binary('elementwise_sub', reverse=True)
    Variable.__mul__ = binary('elementwise_mul')
    Variable.__rmul__ = binary('elementwise_mul', reverse=True)
    Variable.__div__ = binary('elementwise_div')
    Variable.__truediv__ = binary('elementwise_div')
    Variable.__rdiv__ = binary('elementwise_div', reverse=True)
    Variable.__rtruediv__ = binary('elementwise_div', reverse=True)
    Variable.__pow__ = binary('elementwise_pow')
    Variable.__lt__ = binary('less_than')
    Variable.__le__ = binary('less_equal')
    Variable.__gt__ = binary('greater_than')
    Variable.__ge__ = binary('greater_equal')
    Variable.__neg__ = neg


monkey_patch_variable()
