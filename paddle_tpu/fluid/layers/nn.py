"""Neural-network layers (reference: python/paddle/fluid/layers/nn.py).

Each layer builds OpDescs into the current program block; lowering to XLA
happens at Executor compile time.  Shapes are inferred eagerly so later
layers can read ``input.shape`` like the reference's C++ InferShape provides.
"""

import numpy as np

from .. import core
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper
from ..initializer import Normal, Constant
from ..param_attr import ParamAttr

__all__ = [
    'fc', 'embedding', 'conv2d', 'conv3d', 'conv2d_transpose',
    'pool2d', 'pool3d', 'batch_norm', 'layer_norm', 'dropout',
    'softmax', 'softmax_with_cross_entropy', 'cross_entropy',
    'square_error_cost', 'mean', 'mul', 'matmul', 'topk', 'transpose',
    'reshape', 'concat', 'split', 'reduce_sum', 'reduce_mean', 'reduce_max',
    'reduce_min', 'reduce_prod', 'l2_normalize', 'one_hot', 'relu',
    'log', 'autoincreased_step_counter', 'label_smooth', 'clip', 'clip_by_norm',
    'lrn', 'pad',
    'pad2d', 'image_resize', 'resize_bilinear', 'expand', 'stack', 'unstack',
    'squeeze', 'unsqueeze', 'gather', 'scatter', 'slice', 'shape',
    'sigmoid_cross_entropy_with_logits', 'smooth_l1', 'log_loss', 'maxout',
    'prelu', 'leaky_relu', 'soft_relu', 'flatten', 'random_crop', 'im2sequence',
    'hsigmoid', 'nce', 'multiplex', 'dropout', 'layer_norm', 'lstm_unit',
    'linear_chain_crf', 'crf_decoding', 'cos_sim', 'flash_attention',
    'moe_ffn', 'warpctc', 'ctc_greedy_decoder', 'edit_distance', 'roi_pool',
    'conv3d_transpose', 'crop', 'dice_loss', 'image_resize_short',
    'lod_reset', 'mean_iou', 'pad_constant_like', 'rank_loss',
]


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def fc(input,
       size,
       num_flatten_dims=1,
       param_attr=None,
       bias_attr=None,
       act=None,
       is_test=False,
       name=None):
    """Fully-connected layer — mul + elementwise_add + activation
    (reference layers/nn.py:118; mul hits the MXU)."""
    helper = LayerHelper('fc', **locals())
    dtype = helper.input_dtype()
    mul_results = []
    for input_var, param_attr in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        param_shape = [
            _prod(input_shape[num_flatten_dims:])
        ] + [size]
        w = helper.create_parameter(
            attr=param_attr, shape=param_shape, dtype=dtype, is_bias=False)
        tmp = helper.create_variable_for_type_inference(dtype)
        tmp.shape = tuple(input_shape[:num_flatten_dims]) + (size, )
        helper.append_op(
            type='mul',
            inputs={'X': [input_var],
                    'Y': [w]},
            outputs={'Out': [tmp]},
            attrs={
                'x_num_col_dims': num_flatten_dims,
                'y_num_col_dims': 1
            })
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        pre_bias.shape = mul_results[0].shape
        helper.append_op(
            type='sum',
            inputs={'X': mul_results},
            outputs={'Out': pre_bias})
    pre_activation = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_activation)


def embedding(input,
              size,
              is_sparse=False,
              is_distributed=False,
              padding_idx=None,
              param_attr=None,
              dtype='float32'):
    """Lookup-table layer (reference layers/nn.py embedding;
    operators/lookup_table_op.cc).  On TPU the is_sparse path is the same
    dense gather — XLA fuses it; sharded embeddings come from the SPMD layer."""
    helper = LayerHelper('embedding', **locals())
    w = helper.create_parameter(
        attr=helper.param_attr, shape=size, dtype=dtype, is_bias=False)
    tmp = helper.create_variable_for_type_inference(dtype)
    in_shape = tuple(input.shape)
    if in_shape and in_shape[-1] == 1:
        tmp.shape = in_shape[:-1] + (size[1], )
    else:
        tmp.shape = in_shape + (size[1], )
    tmp.lod_level = input.lod_level
    padding_idx = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op(
        type='lookup_table',
        inputs={'Ids': [input],
                'W': [w]},
        outputs={'Out': [tmp]},
        attrs={
            'is_sparse': is_sparse,
            'is_distributed': is_distributed,
            'padding_idx': padding_idx
        })
    return tmp


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def _conv_out_size(i, k, p, s, d=1):
    return (i + 2 * p - (d * (k - 1) + 1)) // s + 1


def conv2d(input,
           num_filters,
           filter_size,
           stride=1,
           padding=0,
           dilation=1,
           groups=None,
           param_attr=None,
           bias_attr=None,
           use_cudnn=True,
           act=None,
           name=None):
    """2-D convolution (reference layers/nn.py conv2d; operators/conv_op.cc).
    ``use_cudnn`` is accepted for API parity and ignored — XLA owns kernels."""
    helper = LayerHelper('conv2d', **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    filter_shape = [num_filters, num_channels // groups] + filter_size

    def _get_default_param_initializer():
        std = (2.0 / (filter_size[0]**2 * num_channels))**0.5
        return Normal(0.0, std, 0)

    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=filter_shape,
        dtype=dtype,
        default_initializer=_get_default_param_initializer())
    pre_bias = helper.create_variable_for_type_inference(dtype)
    n, c, h, w_ = input.shape
    pre_bias.shape = (n, num_filters,
                      _conv_out_size(h, filter_size[0], padding[0], stride[0],
                                     dilation[0]),
                      _conv_out_size(w_, filter_size[1], padding[1], stride[1],
                                     dilation[1]))
    op_type = 'depthwise_conv2d' if (groups == num_channels and
                                     num_channels == num_filters and
                                     groups > 1) else 'conv2d'
    helper.append_op(
        type=op_type,
        inputs={'Input': [input],
                'Filter': [w]},
        outputs={'Output': [pre_bias]},
        attrs={
            'strides': stride,
            'paddings': padding,
            'dilations': dilation,
            'groups': groups,
            'use_cudnn': False,
        })
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d(input,
           num_filters,
           filter_size,
           stride=1,
           padding=0,
           dilation=1,
           groups=None,
           param_attr=None,
           bias_attr=None,
           use_cudnn=True,
           act=None,
           name=None):
    helper = LayerHelper('conv3d', **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1

    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    filter_size = _triple(filter_size)
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    filter_shape = [num_filters, num_channels // groups] + filter_size
    std = (2.0 / (_prod(filter_size) * num_channels))**0.5
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=filter_shape,
        dtype=dtype,
        default_initializer=Normal(0.0, std, 0))
    pre_bias = helper.create_variable_for_type_inference(dtype)
    dims = input.shape
    pre_bias.shape = (dims[0], num_filters) + tuple(
        _conv_out_size(dims[2 + i], filter_size[i], padding[i], stride[i],
                       dilation[i]) for i in range(3))
    helper.append_op(
        type='conv3d',
        inputs={'Input': [input],
                'Filter': [w]},
        outputs={'Output': [pre_bias]},
        attrs={
            'strides': stride,
            'paddings': padding,
            'dilations': dilation,
            'groups': groups
        })
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(input,
                     num_filters,
                     output_size=None,
                     filter_size=None,
                     padding=0,
                     stride=1,
                     dilation=1,
                     groups=None,
                     param_attr=None,
                     bias_attr=None,
                     use_cudnn=True,
                     act=None,
                     name=None):
    helper = LayerHelper('conv2d_transpose', **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    n, c, h, w_ = input.shape
    if filter_size is None:
        output_size = _pair(output_size)
        # reference conv2d_transpose: k = (out + 2p - (in-1)s - 1)//d + 1
        filter_size = [
            (output_size[i] + 2 * padding[i] - (s - 1) * stride[i] - 1) //
            dilation[i] + 1 for i, s in enumerate((h, w_))
        ]
    else:
        filter_size = _pair(filter_size)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    oh = (h - 1) * stride[0] - 2 * padding[0] + dilation[0] * (
        filter_size[0] - 1) + 1
    ow = (w_ - 1) * stride[1] - 2 * padding[1] + dilation[1] * (
        filter_size[1] - 1) + 1
    pre_bias.shape = (n, num_filters, oh, ow)
    helper.append_op(
        type='conv2d_transpose',
        inputs={'Input': [input],
                'Filter': [w]},
        outputs={'Output': [pre_bias]},
        attrs={
            'strides': stride,
            'paddings': padding,
            'dilations': dilation,
            'groups': groups
        })
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(input,
           pool_size=-1,
           pool_type='max',
           pool_stride=1,
           pool_padding=0,
           global_pooling=False,
           use_cudnn=True,
           ceil_mode=False,
           name=None,
           exclusive=True):
    """2-D pooling (reference layers/nn.py pool2d; operators/pool_op.cc)."""
    helper = LayerHelper('pool2d', **locals())
    dtype = helper.input_dtype()
    pool_size = _pair(pool_size)
    pool_stride = _pair(pool_stride)
    pool_padding = _pair(pool_padding)
    out = helper.create_variable_for_type_inference(dtype)
    n, c, h, w = input.shape
    if global_pooling:
        out.shape = (n, c, 1, 1)
    else:
        out.shape = (n, c,
                     _conv_out_size(h, pool_size[0], pool_padding[0],
                                    pool_stride[0]),
                     _conv_out_size(w, pool_size[1], pool_padding[1],
                                    pool_stride[1]))
    helper.append_op(
        type='pool2d',
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={
            'pooling_type': pool_type,
            'ksize': pool_size,
            'global_pooling': global_pooling,
            'strides': pool_stride,
            'paddings': pool_padding,
            'ceil_mode': ceil_mode,
            'exclusive': exclusive,
        })
    return out


def pool3d(input,
           pool_size=-1,
           pool_type='max',
           pool_stride=1,
           pool_padding=0,
           global_pooling=False,
           use_cudnn=True,
           ceil_mode=False,
           name=None):
    helper = LayerHelper('pool3d', **locals())
    dtype = helper.input_dtype()

    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type='pool3d',
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={
            'pooling_type': pool_type,
            'ksize': _triple(pool_size),
            'global_pooling': global_pooling,
            'strides': _triple(pool_stride),
            'paddings': _triple(pool_padding),
            'ceil_mode': ceil_mode,
        })
    return out


def batch_norm(input,
               act=None,
               is_test=False,
               momentum=0.9,
               epsilon=1e-05,
               param_attr=None,
               bias_attr=None,
               data_layout='NCHW',
               in_place=False,
               name=None,
               moving_mean_name=None,
               moving_variance_name=None,
               do_model_average_for_mean_and_var=False,
               fuse_with_relu=False,
               use_global_stats=None):
    """Batch normalization (reference layers/nn.py batch_norm;
    operators/batch_norm_op.cc).  ``use_global_stats``: None = follow
    is_test / clone(for_test); True = always moving statistics; an
    EXPLICIT False keeps batch statistics even through
    clone(for_test=True) — the legacy DSL's documented False mode."""
    helper = LayerHelper('batch_norm', **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    if data_layout == 'NCHW':
        channel_num = input_shape[1]
    else:
        channel_num = input_shape[-1]
    param_shape = [channel_num]

    scale = helper.create_parameter(
        attr=helper.param_attr,
        shape=param_shape,
        dtype=dtype,
        default_initializer=Constant(1.0))
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=param_shape, dtype=dtype, is_bias=True)

    mean = helper.create_parameter(
        attr=ParamAttr(
            name=moving_mean_name,
            initializer=Constant(0.0),
            trainable=False,
            do_model_average=do_model_average_for_mean_and_var),
        shape=param_shape,
        dtype=dtype)
    mean.stop_gradient = True
    variance = helper.create_parameter(
        attr=ParamAttr(
            name=moving_variance_name,
            initializer=Constant(1.0),
            trainable=False,
            do_model_average=do_model_average_for_mean_and_var),
        shape=param_shape,
        dtype=dtype)
    variance.stop_gradient = True

    saved_mean = helper.create_variable_for_type_inference(
        dtype=dtype, stop_gradient=True)
    saved_variance = helper.create_variable_for_type_inference(
        dtype=dtype, stop_gradient=True)
    batch_norm_out = input if in_place else \
        helper.create_variable_for_type_inference(dtype)
    batch_norm_out.shape = input.shape

    helper.append_op(
        type='batch_norm',
        inputs={
            'X': [input],
            'Scale': [scale],
            'Bias': [bias],
            'Mean': [mean],
            'Variance': [variance]
        },
        outputs={
            'Y': [batch_norm_out],
            'MeanOut': [mean],
            'VarianceOut': [variance],
            'SavedMean': [saved_mean],
            'SavedVariance': [saved_variance]
        },
        attrs={
            'momentum': momentum,
            'epsilon': epsilon,
            # is_test is stored RAW: it gates the running-statistics
            # update only.  WHICH statistics normalize is resolved in
            # the lowering from use_global_stats (an EXPLICIT value
            # wins over is_test in both directions; the tri-state
            # "follow is_test" default is represented by OMITTING the
            # attr — None is unserializable on the proto wire) — so
            # use_global_stats=False at test time uses batch statistics
            # WITHOUT the eval batches drifting the checkpointed
            # moving averages
            'is_test': bool(is_test),
            'data_layout': data_layout,
            **({} if use_global_stats is None
               else {'use_global_stats': bool(use_global_stats)}),
        })
    return helper.append_activation(batch_norm_out)


def layer_norm(input,
               scale=True,
               shift=True,
               begin_norm_axis=1,
               epsilon=1e-05,
               param_attr=None,
               bias_attr=None,
               act=None,
               name=None):
    helper = LayerHelper('layer_norm', **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    param_shape = [_prod(input_shape[begin_norm_axis:])]
    inputs = {'X': [input]}
    if scale:
        s = helper.create_parameter(
            attr=helper.param_attr,
            shape=param_shape,
            dtype=dtype,
            default_initializer=Constant(1.0))
        inputs['Scale'] = [s]
    if shift:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=param_shape, dtype=dtype,
            is_bias=True)
        inputs['Bias'] = [b]
    mean_out = helper.create_variable_for_type_inference(
        dtype=dtype, stop_gradient=True)
    variance_out = helper.create_variable_for_type_inference(
        dtype=dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input.shape
    helper.append_op(
        type='layer_norm',
        inputs=inputs,
        outputs={
            'Y': [out],
            'Mean': [mean_out],
            'Variance': [variance_out]
        },
        attrs={'epsilon': epsilon,
               'begin_norm_axis': begin_norm_axis})
    return helper.append_activation(out)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None):
    helper = LayerHelper('dropout', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    mask = helper.create_variable_for_type_inference(
        dtype=x.dtype, stop_gradient=True)
    helper.append_op(
        type='dropout',
        inputs={'X': [x]},
        outputs={'Out': [out],
                 'Mask': [mask]},
        attrs={
            'dropout_prob': dropout_prob,
            'is_test': is_test,
            'seed': seed if seed is not None else 0,
        })
    return out


def softmax(input, use_cudnn=True, name=None):
    helper = LayerHelper('softmax', **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = input.shape
    helper.append_op(
        type='softmax',
        inputs={'X': [input]},
        outputs={'Out': [out]})
    return out


def softmax_with_cross_entropy(logits,
                               label,
                               soft_label=False,
                               ignore_index=-100):
    helper = LayerHelper('softmax_with_cross_entropy', **locals())
    softmax = helper.create_variable_for_type_inference(dtype=logits.dtype)
    softmax.shape = logits.shape
    loss = helper.create_variable_for_type_inference(dtype=logits.dtype)
    loss.shape = tuple(logits.shape[:-1]) + (1, )
    helper.append_op(
        type='softmax_with_cross_entropy',
        inputs={'Logits': [logits],
                'Label': [label]},
        outputs={'Softmax': [softmax],
                 'Loss': [loss]},
        attrs={'soft_label': soft_label,
               'ignore_index': ignore_index})
    return loss


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper('cross_entropy', **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    out.shape = tuple(input.shape[:-1]) + (1, )
    helper.append_op(
        type='cross_entropy',
        inputs={'X': [input],
                'Label': [label]},
        outputs={'Y': [out]},
        attrs={'soft_label': soft_label,
               'ignore_index': ignore_index})
    return out


def square_error_cost(input, label):
    """(input - label)^2 (reference layers/nn.py square_error_cost)."""
    helper = LayerHelper('square_error_cost', **locals())
    minus_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    minus_out.shape = input.shape
    helper.append_op(
        type='elementwise_sub',
        inputs={'X': [input],
                'Y': [label]},
        outputs={'Out': [minus_out]})
    square_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    square_out.shape = input.shape
    helper.append_op(
        type='square',
        inputs={'X': [minus_out]},
        outputs={'Out': [square_out]})
    return square_out


def mean(x, name=None):
    helper = LayerHelper('mean', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = (1, )
    helper.append_op(type='mean', inputs={'X': [x]}, outputs={'Out': [out]})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper('mul', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = tuple(x.shape[:x_num_col_dims]) + tuple(
        y.shape[y_num_col_dims:])
    helper.append_op(
        type='mul',
        inputs={'X': [x],
                'Y': [y]},
        outputs={'Out': [out]},
        attrs={
            'x_num_col_dims': x_num_col_dims,
            'y_num_col_dims': y_num_col_dims
        })
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper('matmul', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    xs = list(x.shape)
    ys = list(y.shape)
    if transpose_x and len(xs) >= 2:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if transpose_y and len(ys) >= 2:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    if len(xs) >= 2 and len(ys) >= 2:
        out.shape = tuple(xs[:-1]) + (ys[-1], )
    helper.append_op(
        type='matmul',
        inputs={'X': [x],
                'Y': [y]},
        outputs={'Out': [out]},
        attrs={
            'transpose_X': transpose_x,
            'transpose_Y': transpose_y,
            'alpha': float(alpha)
        })
    return out


def topk(input, k, name=None):
    helper = LayerHelper('top_k', **locals())
    values = helper.create_variable_for_type_inference(dtype=input.dtype)
    indices = helper.create_variable_for_type_inference(dtype='int64')
    values.shape = tuple(input.shape[:-1]) + (k, )
    indices.shape = values.shape
    helper.append_op(
        type='top_k',
        inputs={'X': [input]},
        outputs={'Out': [values],
                 'Indices': [indices]},
        attrs={'k': k})
    values.stop_gradient = True
    indices.stop_gradient = True
    return values, indices


def transpose(x, perm, name=None):
    helper = LayerHelper('transpose', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = tuple(x.shape[p] for p in perm) if x.shape else ()
    helper.append_op(
        type='transpose',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'axis': list(perm)})
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper('reshape', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    new_shape = list(shape)
    total = _prod([s for s in x.shape]) if all(
        s >= 0 for s in x.shape) else None
    # resolve 0 (copy input dim) first so -1 inference sees them
    resolved = [
        x.shape[i] if s == 0 else s for i, s in enumerate(new_shape)
    ]
    known = _prod([s for s in resolved if s > 0])
    resolved = [
        (total // max(known, 1)) if (s == -1 and total is not None) else s
        for s in resolved
    ]
    out.shape = tuple(resolved)
    inputs = {'X': [x]}
    if actual_shape is not None:
        inputs['Shape'] = [actual_shape]
    helper.append_op(
        type='reshape',
        inputs=inputs,
        outputs={'Out': [out]},
        attrs={'shape': list(shape)})
    return helper.append_activation(out)


def flatten(x, axis=1, name=None):
    helper = LayerHelper('flatten', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = (_prod(x.shape[:axis]), _prod(x.shape[axis:]))
    helper.append_op(
        type='reshape',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'shape': [int(s) for s in out.shape]})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper('concat', **locals())
    out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    shapes = [list(i.shape) for i in input]
    if shapes and all(len(s) == len(shapes[0]) for s in shapes):
        out_shape = list(shapes[0])
        out_shape[axis] = sum(s[axis] for s in shapes)
        out.shape = tuple(out_shape)
    helper.append_op(
        type='concat',
        inputs={'X': input},
        outputs={'Out': [out]},
        attrs={'axis': axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper('split', **locals())
    input_shape = input.shape
    dim_ = dim if dim >= 0 else len(input_shape) + dim
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = [input_shape[dim_] // num] * num
    else:
        num = len(num_or_sections)
        sections = list(num_or_sections)
    outs = []
    for sec in sections:
        o = helper.create_variable_for_type_inference(dtype=input.dtype)
        s = list(input_shape)
        s[dim_] = sec
        o.shape = tuple(s)
        outs.append(o)
    helper.append_op(
        type='split',
        inputs={'X': [input]},
        outputs={'Out': outs},
        attrs={
            'num': num_or_sections if isinstance(num_or_sections, int) else 0,
            'sections': sections,
            'axis': dim_
        })
    return outs


def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    if dim is not None and not isinstance(dim, (list, tuple)):
        dim = [dim]
    shape = list(input.shape)
    if dim is None or not shape:
        out.shape = (1, )
    else:
        dims = sorted(d % len(shape) for d in dim)
        if keep_dim:
            for d in dims:
                shape[d] = 1
            out.shape = tuple(shape)
        else:
            out.shape = tuple(s for i, s in enumerate(shape)
                              if i not in dims) or (1, )
    helper.append_op(
        type=op_type,
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={
            'dim': dim if dim is not None else [0],
            'keep_dim': keep_dim,
            'reduce_all': dim is None
        })
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_sum', input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_mean', input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_max', input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_min', input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce('reduce_prod', input, dim, keep_dim, name)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper('l2_normalize', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    norm = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type='norm',
        inputs={'X': [x]},
        outputs={'Out': [out],
                 'Norm': [norm]},
        attrs={'axis': 1 if axis is None else axis,
               'epsilon': epsilon})
    return out


def one_hot(input, depth):
    helper = LayerHelper('one_hot', **locals())
    out = helper.create_variable_for_type_inference(dtype='float32')
    helper.append_op(
        type='one_hot',
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={'depth': depth})
    out.stop_gradient = True
    return out


def relu(x, name=None):
    helper = LayerHelper('relu', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type='relu', inputs={'X': [x]}, outputs={'Out': [out]})
    return out


def log(x, name=None):
    helper = LayerHelper('log', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(type='log', inputs={'X': [x]}, outputs={'Out': [out]})
    return out


def leaky_relu(x, alpha=0.02, name=None):
    helper = LayerHelper('leaky_relu', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(
        type='leaky_relu',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'alpha': alpha})
    return out


def soft_relu(x, threshold=40.0, name=None):
    helper = LayerHelper('soft_relu', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(
        type='soft_relu',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'threshold': threshold})
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper('prelu', **locals())
    if mode not in ('all', 'channel', 'element'):
        raise ValueError("mode should be 'all', 'channel' or 'element'")
    alpha_shape = [1]
    if mode == 'channel':
        alpha_shape = [1, x.shape[1], 1, 1]
    elif mode == 'element':
        alpha_shape = list(x.shape)
    alpha = helper.create_parameter(
        attr=helper.param_attr,
        shape=alpha_shape,
        dtype='float32',
        is_bias=False,
        default_initializer=Constant(0.25))
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(
        type='prelu',
        inputs={'X': [x],
                'Alpha': [alpha]},
        outputs={'Out': [out]},
        attrs={'mode': mode})
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper('maxout', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    n, c, h, w = x.shape
    out.shape = (n, c // groups, h, w)
    helper.append_op(
        type='maxout',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'groups': groups})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper('lrn', **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(
        input.dtype, stop_gradient=True)
    out.shape = input.shape
    helper.append_op(
        type='lrn',
        inputs={'X': [input]},
        outputs={'Out': [out],
                 'MidOut': [mid]},
        attrs={'n': n,
               'k': k,
               'alpha': alpha,
               'beta': beta})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper('pad', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    if getattr(x, 'shape', None):
        shape = list(x.shape)
        for i in range(min(len(shape), len(paddings) // 2)):
            if shape[i] is not None and int(shape[i]) >= 0:
                shape[i] = int(shape[i]) + paddings[2 * i] + \
                    paddings[2 * i + 1]
        out.shape = tuple(shape)
    helper.append_op(
        type='pad',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'paddings': list(paddings),
               'pad_value': float(pad_value)})
    return out


def pad2d(input,
          paddings=[0, 0, 0, 0],
          mode='constant',
          pad_value=0.0,
          data_format='NCHW',
          name=None):
    helper = LayerHelper('pad2d', **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type='pad2d',
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={
            'paddings': list(paddings),
            'mode': mode,
            'pad_value': float(pad_value)
        })
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample='BILINEAR'):
    helper = LayerHelper('bilinear_interp', **locals())
    if out_shape is None:
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    out = helper.create_variable_for_type_inference(input.dtype)
    out.shape = (input.shape[0], input.shape[1], out_shape[0], out_shape[1])
    op_type = 'bilinear_interp' if resample == 'BILINEAR' else 'nearest_interp'
    helper.append_op(
        type=op_type,
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={'out_h': out_shape[0],
               'out_w': out_shape[1]})
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, 'BILINEAR')


def expand(x, expand_times, name=None):
    helper = LayerHelper('expand', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = tuple(
        s * t for s, t in zip(x.shape, expand_times))
    helper.append_op(
        type='expand',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'expand_times': list(expand_times)})
    return out


def stack(x, axis=0):
    helper = LayerHelper('stack', **locals())
    if isinstance(x, Variable):
        x = [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(
        type='stack',
        inputs={'X': x},
        outputs={'Y': [out]},
        attrs={'axis': axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper('unstack', **locals())
    if num is None:
        num = x.shape[axis]
    outs = [
        helper.create_variable_for_type_inference(x.dtype) for _ in range(num)
    ]
    helper.append_op(
        type='unstack',
        inputs={'X': [x]},
        outputs={'Y': outs},
        attrs={'axis': axis,
               'num': num})
    return outs


def squeeze(input, axes, name=None):
    helper = LayerHelper('squeeze', **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type='squeeze',
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={'axes': list(axes)})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper('unsqueeze', **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type='unsqueeze',
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={'axes': list(axes)})
    return out


def gather(input, index):
    helper = LayerHelper('gather', **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type='gather',
        inputs={'X': [input],
                'Index': [index]},
        outputs={'Out': [out]})
    return out


def scatter(input, index, updates, name=None):
    helper = LayerHelper('scatter', **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type='scatter',
        inputs={'X': [input],
                'Ids': [index],
                'Updates': [updates]},
        outputs={'Out': [out]})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper('slice', **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    if getattr(input, 'shape', None):
        # mirror the runtime's Python slice semantics (negative indices,
        # INT_MAX-as-open-end); unknown dims (-1) stay unknown
        INT_MAX = 2**31 - 1
        shape = list(input.shape)
        for ax, s, e in zip(axes, starts, ends):
            if not (0 <= ax < len(shape)):
                continue
            dim = shape[ax]
            if dim is None or int(dim) < 0:
                continue
            import builtins
            shape[ax] = len(range(int(dim))[builtins.slice(
                None if s <= -INT_MAX else s,
                None if e >= INT_MAX else e)])
        out.shape = tuple(shape)
    helper.append_op(
        type='slice',
        inputs={'Input': [input]},
        outputs={'Out': [out]},
        attrs={
            'axes': list(axes),
            'starts': list(starts),
            'ends': list(ends)
        })
    return out


def shape(input):
    helper = LayerHelper('shape', **locals())
    out = helper.create_variable_for_type_inference(dtype='int32')
    helper.append_op(
        type='shape', inputs={'Input': [input]}, outputs={'Out': [out]})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper('clip', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(
        type='clip',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'min': min,
               'max': max})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper('clip_by_norm', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(
        type='clip_by_norm',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'max_norm': max_norm})
    return out


def label_smooth(label,
                 prior_dist=None,
                 epsilon=0.1,
                 dtype='float32',
                 name=None):
    helper = LayerHelper('label_smooth', **locals())
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {'X': [label]}
    if prior_dist is not None:
        inputs['PriorDist'] = [prior_dist]
    helper.append_op(
        type='label_smooth',
        inputs=inputs,
        outputs={'Out': [out]},
        attrs={'epsilon': float(epsilon)})
    return out


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    helper = LayerHelper('sigmoid_cross_entropy_with_logits', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(
        type='sigmoid_cross_entropy_with_logits',
        inputs={'X': [x],
                'Label': [label]},
        outputs={'Out': [out]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper('smooth_l1_loss', **locals())
    diff = helper.create_variable_for_type_inference(dtype=x.dtype)
    loss = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {'X': [x], 'Y': [y]}
    if inside_weight is not None:
        inputs['InsideWeight'] = [inside_weight]
    if outside_weight is not None:
        inputs['OutsideWeight'] = [outside_weight]
    helper.append_op(
        type='smooth_l1_loss',
        inputs=inputs,
        outputs={'Diff': [diff],
                 'Out': [loss]},
        attrs={'sigma': sigma if sigma is not None else 1.0})
    return loss


def log_loss(input, label, epsilon=1e-4, name=None):
    helper = LayerHelper('log_loss', **locals())
    loss = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type='log_loss',
        inputs={'Predicted': [input],
                'Labels': [label]},
        outputs={'Loss': [loss]},
        attrs={'epsilon': epsilon})
    return loss


def multiplex(inputs, index):
    helper = LayerHelper('multiplex', **locals())
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(
        type='multiplex',
        inputs={'X': inputs,
                'Ids': [index]},
        outputs={'Out': [out]})
    return out


def random_crop(x, shape, seed=None):
    helper = LayerHelper('random_crop', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type='random_crop',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'shape': list(shape)})
    return out


def im2sequence(input,
                filter_size=1,
                stride=1,
                padding=0,
                input_image_size=None,
                out_stride=1,
                name=None):
    helper = LayerHelper('im2sequence', **locals())
    filter_size = _pair(filter_size)
    stride = _pair(stride)
    if not isinstance(padding, (list, tuple)):
        padding = [padding] * 4
    elif len(padding) == 2:
        padding = list(padding) * 2
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        type='im2sequence',
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={
            'kernels': filter_size,
            'strides': stride,
            'paddings': list(padding)
        })
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Global step counter var incremented once per executor run
    (reference layers/nn.py autoincreased_step_counter)."""
    helper = LayerHelper('global_step_counter')
    counter_name = counter_name or '@STEP_COUNTER@'
    counter = helper.create_or_get_global_variable(
        name=counter_name,
        dtype='int64',
        shape=[1],
        persistable=True)
    if counter.op is None:
        helper.set_variable_initializer(
            counter, initializer=Constant(value=begin - 1))
        counter.op = helper.append_op(
            type='increment',
            inputs={'X': [counter]},
            outputs={'Out': [counter]},
            attrs={'step': float(step)})
        counter.stop_gradient = True
    return counter


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Hierarchical sigmoid (reference operators/hsigmoid_op.cc).  Lowered as
    a dense binary-code formulation."""
    helper = LayerHelper('hsigmoid', **locals())
    dtype = helper.input_dtype()
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[num_classes - 1, input.shape[1]],
        dtype=dtype)
    inputs = {'X': [input], 'W': [w], 'Label': [label]}
    if helper.bias_attr:
        bias = helper.create_parameter(
            attr=helper.bias_attr,
            shape=[1, num_classes - 1],
            dtype=dtype,
            is_bias=True)
        inputs['Bias'] = [bias]
    out = helper.create_variable_for_type_inference(dtype)
    pre_out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type='hsigmoid',
        inputs=inputs,
        outputs={'Out': [out],
                 'PreOut': [pre_out]},
        attrs={'num_classes': num_classes})
    return out


def nce(input,
        label,
        num_total_classes,
        sample_weight=None,
        param_attr=None,
        bias_attr=None,
        num_neg_samples=None,
        name=None):
    """Noise-contrastive estimation loss (reference operators/nce_op.cc)."""
    helper = LayerHelper('nce', **locals())
    dtype = helper.input_dtype()
    dim = input.shape[1]
    num_neg_samples = 10 if num_neg_samples is None else num_neg_samples
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_total_classes, dim], dtype=dtype)
    b = helper.create_parameter(
        attr=helper.bias_attr,
        shape=[num_total_classes, 1],
        dtype=dtype,
        is_bias=True)
    cost = helper.create_variable_for_type_inference(dtype)
    sample_logits = helper.create_variable_for_type_inference(dtype)
    sample_labels = helper.create_variable_for_type_inference(dtype='int64')
    helper.append_op(
        type='nce',
        inputs={'Input': [input],
                'Label': [label],
                'Weight': [w],
                'Bias': [b]},
        outputs={
            'Cost': [cost],
            'SampleLogits': [sample_logits],
            'SampleLabels': [sample_labels]
        },
        attrs={
            'num_total_classes': int(num_total_classes),
            'num_neg_samples': int(num_neg_samples)
        })
    return cost


def lstm_unit(x_t,
              hidden_t_prev,
              cell_t_prev,
              forget_bias=0.0,
              param_attr=None,
              bias_attr=None,
              name=None):
    """Single LSTM step built from fc + lstm_unit op
    (reference layers/nn.py lstm_unit)."""
    helper = LayerHelper('lstm_unit', **locals())
    size = cell_t_prev.shape[1]
    concat_out = concat(input=[x_t, hidden_t_prev], axis=1)
    fc_out = fc(input=concat_out,
                size=4 * size,
                param_attr=param_attr,
                bias_attr=bias_attr)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    c.shape = cell_t_prev.shape
    h.shape = hidden_t_prev.shape
    helper.append_op(
        type='lstm_unit',
        inputs={'X': [fc_out],
                'C_prev': [cell_t_prev]},
        outputs={'C': [c],
                 'H': [h]},
        attrs={'forget_bias': forget_bias})
    return h, c


def linear_chain_crf(input, label, param_attr=None):
    """Linear-chain CRF negative log-likelihood per sequence
    (reference layers/nn.py linear_chain_crf;
    operators/linear_chain_crf_op.cc).  Creates the [size+2, size]
    transition parameter (row 0 start, row 1 end weights)."""
    helper = LayerHelper('linear_chain_crf', **locals())
    size = input.shape[-1]
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[size + 2, size], dtype='float32')
    alpha = helper.create_variable_for_type_inference('float32')
    emission_exps = helper.create_variable_for_type_inference('float32')
    transition_exps = helper.create_variable_for_type_inference('float32')
    log_likelihood = helper.create_variable_for_type_inference('float32')
    helper.append_op(
        type='linear_chain_crf',
        inputs={'Emission': [input],
                'Transition': [transition],
                'Label': [label]},
        outputs={
            'Alpha': [alpha],
            'EmissionExps': [emission_exps],
            'TransitionExps': [transition_exps],
            'LogLikelihood': [log_likelihood],
        })
    return log_likelihood


def crf_decoding(input, param_attr, label=None):
    """Viterbi decode with the CRF transition parameter (reference
    layers/nn.py crf_decoding; operators/crf_decoding_op.cc).  With a
    label input, emits the per-token correctness indicator instead."""
    helper = LayerHelper('crf_decoding', **locals())
    try:
        transition = helper.get_parameter(param_attr.name)
    except ValueError:
        # decoding-only program (built fresh, weights loaded afterwards by
        # name): create the slot zero-initialized — deterministic garbage
        # until load_persistables fills it, never silent random output
        import warnings
        warnings.warn(
            "crf_decoding: transition parameter %r does not exist in this "
            "program; creating it zero-initialized (expecting "
            "load_persistables to fill it)" % param_attr.name)
        size = input.shape[-1]
        transition = helper.create_parameter(
            attr=helper.param_attr, shape=[size + 2, size],
            dtype='float32', default_initializer=Constant(0.0))
    viterbi_path = helper.create_variable_for_type_inference('int64')
    viterbi_path.lod_level = input.lod_level
    inputs = {'Emission': [input], 'Transition': [transition]}
    if label is not None:
        inputs['Label'] = [label]
    helper.append_op(
        type='crf_decoding',
        inputs=inputs,
        outputs={'ViterbiPath': [viterbi_path]})
    return viterbi_path


def cos_sim(X, Y):
    """Row-wise cosine similarity [B, 1] (reference layers/nn.py cos_sim;
    operators/cos_sim_op.cc)."""
    helper = LayerHelper('cos_sim', **locals())
    out = helper.create_variable_for_type_inference(X.dtype)
    xnorm = helper.create_variable_for_type_inference(X.dtype)
    ynorm = helper.create_variable_for_type_inference(X.dtype)
    out.shape = (X.shape[0], 1)
    helper.append_op(
        type='cos_sim',
        inputs={'X': [X],
                'Y': [Y]},
        outputs={'Out': [out],
                 'XNorm': [xnorm],
                 'YNorm': [ynorm]})
    return out


def flash_attention(q, k, v, num_heads=None, causal=False, scale=None,
                    impl='auto', sp_axis='sp', name=None):
    """Fused scaled-dot-product attention (TPU-native extension).

    The reference builds attention out of matmul/softmax primitives
    (nets.py scaled_dot_product_attention) with no sequence parallelism;
    here ONE op lowers to ring attention over a context-parallel 'sp' mesh
    axis, a Pallas flash kernel on a single TPU chip, or dense XLA —
    see ops/attention_ops.py.

    q, k, v: [batch, seq, heads, head_dim] Variables, or
             [batch, seq, heads*head_dim] with num_heads given.
    impl: 'auto' | 'ring' | 'ulysses' | 'pallas' | 'dense'.
    Returns a Variable with q's shape.
    """
    helper = LayerHelper('flash_attention', **locals())
    squeeze_back = False
    if len(q.shape) == 3:
        if not num_heads:
            raise ValueError('3-D q/k/v need num_heads to split the fused '
                             'head dim')
        squeeze_back = True
        q = reshape(q, [0, 0, num_heads, q.shape[-1] // num_heads])
        k = reshape(k, [0, 0, num_heads, k.shape[-1] // num_heads])
        v = reshape(v, [0, 0, num_heads, v.shape[-1] // num_heads])
    out = helper.create_variable_for_type_inference(q.dtype)
    # attention output carries V's head_dim (may differ from Q's)
    out.shape = tuple(q.shape[:-1]) + (v.shape[-1], )
    helper.append_op(
        type='flash_attention',
        inputs={'Q': [q], 'K': [k], 'V': [v]},
        outputs={'Out': [out]},
        attrs={
            'causal': bool(causal),
            'scale': float(scale) if scale else -1.0,
            'impl': impl,
            'sp_axis': sp_axis,
        })
    if squeeze_back:
        out = reshape(out, [0, 0, int(num_heads) * int(v.shape[-1])])
    return out


def moe_ffn(input, num_experts, d_ff, capacity_factor=1.25,
            ep_axis='ep', param_attr=None, bias_attr=None, name=None):
    """Switch-style Mixture-of-Experts FFN (TPU-native extension; the
    reference predates MoE).

    Top-1 routing with a static per-expert capacity (GShard dense
    dispatch, ops/moe_ops.py): over-capacity tokens pass through with
    zero expert output, the gate probability scales the kept ones so
    the router trains.  Expert weights carry a leading [num_experts,
    ...] axis annotated PartitionSpec(ep_axis): under a
    ParallelExecutor mesh with an 'ep' axis GSPMD shards the experts
    and partitions the dispatch/combine einsums — expert parallelism
    through the same annotation mechanism tensor-parallel fc uses.
    (For the hand-scheduled all_to_all variant outside the Program IR
    see paddle_tpu.parallel.moe_ffn_spmd.)

    input: [..., d_model] Variable.  Returns same shape.
    """
    helper = LayerHelper('moe_ffn', **locals())
    from ...parallel import shard as _shard
    import copy as _copy
    dtype = helper.input_dtype()
    d = int(input.shape[-1])
    e, dff = int(num_experts), int(d_ff)

    def _attr(base, suffix):
        # one user attr names FOUR differently-shaped weights: suffix
        # the name per weight so a named ParamAttr doesn't collide on
        # the shared-parameter path
        if base is None or base is False or getattr(base, 'name',
                                                    None) is None:
            return base
        a = _copy.copy(base)
        a.name = '%s.%s' % (base.name, suffix)
        return a

    gate_w = helper.create_parameter(attr=_attr(helper.param_attr, 'gate'),
                                     shape=[d, e], dtype=dtype)
    w1 = helper.create_parameter(attr=_attr(helper.param_attr, 'w1'),
                                 shape=[e, d, dff], dtype=dtype)
    w2 = helper.create_parameter(attr=_attr(helper.param_attr, 'w2'),
                                 shape=[e, dff, d], dtype=dtype)
    experts = [w1, w2]
    inputs = {'X': [input], 'GateW': [gate_w], 'W1': [w1], 'W2': [w2]}
    if bias_attr is not False:
        # bias_attr=False means NO bias at all (the repo-wide fc/conv
        # convention), not a frozen zero parameter
        b1 = helper.create_parameter(attr=_attr(helper.bias_attr, 'b1'),
                                     shape=[e, dff], dtype=dtype,
                                     is_bias=True)
        b2 = helper.create_parameter(attr=_attr(helper.bias_attr, 'b2'),
                                     shape=[e, d], dtype=dtype,
                                     is_bias=True)
        experts += [b1, b2]
        inputs['B1'] = [b1]
        inputs['B2'] = [b2]
    for p in experts:
        _shard(p, ep_axis)          # leading expert axis over 'ep'
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(input.shape)
    helper.append_op(
        type='moe_ffn',
        inputs=inputs,
        outputs={'Out': [out]},
        attrs={'capacity_factor': float(capacity_factor),
               'ep_axis': ep_axis})
    return out


def warpctc(input, label, blank=0, norm_by_times=False):
    """CTC loss over a LoD batch of logit sequences (reference nn.py
    warpctc; operators/warpctc_op.cc).  Computed natively as a lax.scan
    alpha recursion (ops/ctc_ops.py) instead of wrapping warp-ctc; the
    gradient comes from autodiff rather than the WarpCTCGrad side tensor.
    Returns per-sequence loss (N, 1)."""
    helper = LayerHelper('warpctc', **locals())
    loss_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    loss_out.shape = (-1, 1)
    helper.append_op(
        type='warpctc',
        inputs={'Logits': [input],
                'Label': [label]},
        outputs={'Loss': [loss_out]},
        attrs={'blank': blank,
               'norm_by_times': norm_by_times})
    return loss_out


def ctc_greedy_decoder(input, blank, name=None):
    """Best-path CTC decode: argmax per step, merge repeats, drop blanks
    (reference nn.py ctc_greedy_decoder = top_k + ctc_align)."""
    helper = LayerHelper('ctc_greedy_decoder', **locals())
    argmax_out = helper.create_variable_for_type_inference(dtype='int64')
    argmax_out.shape = tuple(input.shape[:-1])
    helper.append_op(
        type='argmax',
        inputs={'X': [input]},
        outputs={'Out': [argmax_out]},
        attrs={'axis': -1})
    out = helper.create_variable_for_type_inference(dtype='int64')
    helper.append_op(
        type='ctc_align',
        inputs={'Input': [argmax_out]},
        outputs={'Output': [out]},
        attrs={'blank': blank,
               'merge_repeated': True})
    out.stop_gradient = True
    return out


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  name=None):
    """Levenshtein distance between hypothesis and reference sequences
    (reference nn.py edit_distance; operators/edit_distance_op.cc).
    Returns (distance (N, 1), sequence_num (1,))."""
    from .sequence import sequence_erase
    helper = LayerHelper('edit_distance', **locals())
    if ignored_tokens is not None and len(ignored_tokens) > 0:
        input = sequence_erase(input, ignored_tokens)
        label = sequence_erase(label, ignored_tokens)
    edit_distance_out = helper.create_variable_for_type_inference(
        dtype='float32')
    sequence_num = helper.create_variable_for_type_inference(dtype='int64')
    helper.append_op(
        type='edit_distance',
        inputs={'Hyps': [input],
                'Refs': [label]},
        outputs={'Out': [edit_distance_out],
                 'SequenceNum': [sequence_num]},
        attrs={'normalized': normalized})
    edit_distance_out.stop_gradient = True
    sequence_num.stop_gradient = True
    return edit_distance_out, sequence_num


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    """Max-pool features inside each region of interest (reference nn.py
    roi_pool; operators/roi_pool_op.cc).  rois: LoD (num_rois, 4) boxes
    per image."""
    helper = LayerHelper('roi_pool', **locals())
    dtype = helper.input_dtype()
    out = helper.create_variable_for_type_inference(dtype)
    argmaxes = helper.create_variable_for_type_inference(dtype='int32')
    out.shape = (-1, input.shape[1], pooled_height, pooled_width)
    helper.append_op(
        type='roi_pool',
        inputs={'X': [input],
                'ROIs': [rois]},
        outputs={'Out': [out],
                 'Argmax': [argmaxes]},
        attrs={
            'pooled_height': pooled_height,
            'pooled_width': pooled_width,
            'spatial_scale': spatial_scale
        })
    return out


def conv3d_transpose(input,
                     num_filters,
                     output_size=None,
                     filter_size=None,
                     padding=0,
                     stride=1,
                     dilation=1,
                     groups=None,
                     param_attr=None,
                     bias_attr=None,
                     use_cudnn=True,
                     act=None,
                     name=None):
    """Transposed 3D convolution (reference nn.py:2426 conv3d_transpose;
    operators/conv_transpose_op.cc)."""

    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    helper = LayerHelper('conv3d_transpose', **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    n, c, d, h, w_ = input.shape
    if filter_size is None:
        output_size = _triple(output_size)
        # reference conv3d_transpose: k = (out + 2p - (in-1)s - 1)//d + 1
        filter_size = [
            (output_size[i] + 2 * padding[i] - (s - 1) * stride[i] - 1) //
            dilation[i] + 1 for i, s in enumerate((d, h, w_))
        ]
    else:
        filter_size = _triple(filter_size)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    out_spatial = [
        (s - 1) * stride[i] - 2 * padding[i] + dilation[i] *
        (filter_size[i] - 1) + 1 for i, s in enumerate((d, h, w_))
    ]
    pre_bias.shape = tuple([n, num_filters] + out_spatial)
    helper.append_op(
        type='conv3d_transpose',
        inputs={'Input': [input],
                'Filter': [w]},
        outputs={'Output': [pre_bias]},
        attrs={
            'strides': stride,
            'paddings': padding,
            'dilations': dilation,
            'groups': groups
        })
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def crop(x, shape=None, offsets=None, name=None):
    """Crop x to ``shape`` starting at ``offsets`` (reference nn.py:5453;
    operators/crop_op.cc).  ``shape`` may be a Variable whose dims give
    the target shape."""
    helper = LayerHelper('crop', **locals())
    inputs = {'X': [x]}
    attrs = {}
    if shape is None:
        raise ValueError(
            'crop: shape is required — a list of output dims or a '
            'Variable whose shape is the target (reference nn.py:5453 '
            'asserts the same)')
    if isinstance(shape, Variable):
        inputs['Y'] = [shape]
        out_shape = shape.shape
    else:
        attrs['shape'] = list(shape)
        out_shape = tuple(shape)
    if offsets is None:
        offsets = [0] * len(x.shape)
    attrs['offsets'] = list(offsets)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = tuple(out_shape)
    helper.append_op(
        type='crop', inputs=inputs, outputs={'Out': [out]}, attrs=attrs)
    return out


def dice_loss(input, label, epsilon=0.00001):
    """Dice loss for binary segmentation (reference nn.py:5032): a pure
    composition — one_hot the labels, per-sample intersection and area
    sums over every non-batch dim, 1 - 2I/(A + eps), batch mean."""
    label = one_hot(label, depth=input.shape[-1])
    reduce_dim = list(range(1, len(input.shape)))
    inse = reduce_sum(input * label, dim=reduce_dim)
    dice_denominator = reduce_sum(input, dim=reduce_dim) + reduce_sum(
        label, dim=reduce_dim)
    dice_score = 1 - inse * 2 / (dice_denominator + epsilon)
    return reduce_mean(dice_score)


def image_resize_short(input, out_short_len, resample='BILINEAR'):
    """Resize so the short image edge equals out_short_len, keeping the
    aspect ratio (reference nn.py:5175)."""
    in_shape = input.shape
    if len(in_shape) != 4:
        raise ValueError(
            'The rank of input must be 4 (num_batches, channels, in_h, '
            'in_w).')
    hw = list(in_shape[2:4])
    short_idx = hw.index(min(hw))
    long_idx = 1 - short_idx
    out_shape = list(hw)
    out_shape[short_idx] = out_short_len
    out_shape[long_idx] = int(
        float(out_shape[long_idx]) *
        (float(out_short_len) / float(hw[short_idx])) + 0.5)
    return image_resize(input=input, out_shape=out_shape, resample=resample)


def lod_reset(x, y=None, target_lod=None):
    """Re-assign x's LoD from y or target_lod (reference nn.py:4625;
    operators/lod_reset_op.cc).  Under the padded+SEQLEN lowering the
    dense payload is unchanged; the new lengths ride the side-band."""
    helper = LayerHelper('lod_reset', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = tuple(x.shape)
    out.lod_level = 1
    if y is not None:
        helper.append_op(
            type='lod_reset', inputs={'X': [x], 'Y': [y]},
            outputs={'Out': [out]})
    elif target_lod is not None:
        helper.append_op(
            type='lod_reset', inputs={'X': [x]},
            outputs={'Out': [out]},
            attrs={'target_lod': [int(v) for v in target_lod]})
    else:
        raise ValueError('lod_reset: y and target_lod cannot both be None')
    return out


def mean_iou(input, label, num_classes):
    """Mean intersection-over-union (reference nn.py:5403;
    operators/mean_iou_op.cc).  Returns (mean_iou, out_wrong,
    out_correct)."""
    helper = LayerHelper('mean_iou', **locals())
    iou = helper.create_variable_for_type_inference('float32')
    out_wrong = helper.create_variable_for_type_inference('int32')
    out_correct = helper.create_variable_for_type_inference('int32')
    iou.shape = (1, )
    # per-class counts (reference mean_iou_op.cc SetOutputDim)
    out_wrong.shape = (num_classes, )
    out_correct.shape = (num_classes, )
    for v in (iou, out_wrong, out_correct):
        v.stop_gradient = True
    helper.append_op(
        type='mean_iou',
        inputs={'Predictions': [input],
                'Labels': [label]},
        outputs={
            'OutMeanIou': [iou],
            'OutWrong': [out_wrong],
            'OutCorrect': [out_correct]
        },
        attrs={'num_classes': num_classes})
    return iou, out_wrong, out_correct


def pad_constant_like(x, y, pad_value=0., name=None):
    """Pad y with pad_value so its shape matches x (reference nn.py:4849;
    operators/pad_constant_like_op.cc)."""
    helper = LayerHelper('pad_constant_like', **locals())
    out = helper.create_variable_for_type_inference(y.dtype)
    out.shape = tuple(x.shape)
    helper.append_op(
        type='pad_constant_like',
        inputs={'X': [x],
                'Y': [y]},
        outputs={'Out': [out]},
        attrs={'pad_value': float(pad_value)})
    return out


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss (reference nn.py:5551;
    operators/rank_loss_op.cc)."""
    helper = LayerHelper('rank_loss', **locals())
    for v, n in ((label, 'label'), (left, 'left'), (right, 'right')):
        if not isinstance(v, Variable):
            raise ValueError('rank_loss: %s must be a Variable' % n)
    out = helper.create_variable_for_type_inference('float32')
    out.shape = tuple(left.shape)
    helper.append_op(
        type='rank_loss',
        inputs={'Label': [label],
                'Left': [left],
                'Right': [right]},
        outputs={'Out': [out]})
    return out
