"""Auto-generated op wrapper layers
(reference: python/paddle/fluid/layers/ops.py + layer_function_generator.py)."""

from ..layer_helper import LayerHelper

__activations__ = [
    'sigmoid', 'logsigmoid', 'exp', 'tanh', 'tanh_shrink', 'softshrink',
    'sqrt', 'abs', 'ceil', 'floor', 'cos', 'sin', 'round', 'reciprocal',
    'log', 'square', 'softplus', 'softsign', 'brelu', 'leaky_relu',
    'soft_relu', 'elu', 'relu6', 'pow', 'stanh', 'hard_sigmoid', 'swish',
    'relu', 'thresholded_relu', 'hard_shrink',
]

__all__ = __activations__ + [
    'elementwise_add', 'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'elementwise_max', 'elementwise_min',
    'elementwise_pow', 'uniform_random', 'gaussian_random',
    'uniform_random_batch_size_like', 'gaussian_random_batch_size_like',
    'scale', 'cumsum', 'clip', 'clip_by_norm', 'logical_and', 'logical_or',
    'logical_xor', 'logical_not', 'sampling_id',
]


def _unary_layer(op_type):
    def func(x, name=None, **kwargs):
        helper = LayerHelper(op_type, **locals())
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        out.shape = x.shape
        helper.append_op(
            type=op_type,
            inputs={'X': [x]},
            outputs={'Out': [out]},
            attrs=kwargs)
        return out

    func.__name__ = op_type
    func.__doc__ = 'Elementwise %s (XLA-fused).' % op_type
    return func


for _act in __activations__:
    globals()[_act] = _unary_layer(_act)


def _elementwise_layer(op_type):
    def func(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, **locals())
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        out.shape = x.shape
        helper.append_op(
            type=op_type,
            inputs={'X': [x],
                    'Y': [y]},
            outputs={'Out': [out]},
            attrs={'axis': axis})
        return helper.append_activation(out)

    func.__name__ = op_type
    return func


for _ew in ('add', 'sub', 'mul', 'div', 'max', 'min', 'pow'):
    globals()['elementwise_' + _ew] = _elementwise_layer('elementwise_' + _ew)


def _logical_layer(op_type, binary=True):
    def func(x, y=None, out=None, name=None):
        helper = LayerHelper(op_type, **locals())
        if out is None:
            out = helper.create_variable_for_type_inference(dtype='bool')
        inputs = {'X': [x]}
        if binary:
            inputs['Y'] = [y]
        helper.append_op(
            type=op_type, inputs=inputs, outputs={'Out': [out]})
        return out

    func.__name__ = op_type
    return func


logical_and = _logical_layer('logical_and')
logical_or = _logical_layer('logical_or')
logical_xor = _logical_layer('logical_xor')
logical_not = _logical_layer('logical_not', binary=False)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype='int64'):
    """Sample one column index per row of a probability matrix (reference
    operators/sampling_id_op.cc; layers/ops.py export)."""
    helper = LayerHelper('sampling_id', **locals())
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = (x.shape[0], )
    helper.append_op(
        type='sampling_id',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'min': float(min), 'max': float(max), 'seed': int(seed)})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper('scale', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(
        type='scale',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={
            'scale': float(scale),
            'bias': float(bias),
            'bias_after_scale': bias_after_scale
        })
    return helper.append_activation(out)


def cumsum(x, axis=None, exclusive=None, reverse=None):
    helper = LayerHelper('cumsum', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    attrs = {}
    if axis is not None:
        attrs['axis'] = axis
    if exclusive is not None:
        attrs['exclusive'] = exclusive
    if reverse is not None:
        attrs['reverse'] = reverse
    helper.append_op(
        type='cumsum', inputs={'X': [x]}, outputs={'Out': [out]}, attrs=attrs)
    return out


def uniform_random(shape, dtype='float32', min=-1.0, max=1.0, seed=0):
    helper = LayerHelper('uniform_random', **locals())
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(shape)
    helper.append_op(
        type='uniform_random',
        outputs={'Out': [out]},
        attrs={
            'shape': list(shape),
            'dtype': out.dtype,
            'min': min,
            'max': max,
            'seed': seed
        })
    out.stop_gradient = True
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype='float32'):
    helper = LayerHelper('gaussian_random', **locals())
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(shape)
    helper.append_op(
        type='gaussian_random',
        outputs={'Out': [out]},
        attrs={
            'shape': list(shape),
            'dtype': out.dtype,
            'mean': mean,
            'std': std,
            'seed': seed
        })
    out.stop_gradient = True
    return out


def uniform_random_batch_size_like(input,
                                   shape,
                                   dtype='float32',
                                   input_dim_idx=0,
                                   output_dim_idx=0,
                                   min=-1.0,
                                   max=1.0,
                                   seed=0):
    helper = LayerHelper('uniform_random_batch_size_like', **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type='uniform_random_batch_size_like',
        inputs={'Input': [input]},
        outputs={'Out': [out]},
        attrs={
            'shape': list(shape),
            'input_dim_idx': input_dim_idx,
            'output_dim_idx': output_dim_idx,
            'min': min,
            'max': max,
            'seed': seed,
            'dtype': out.dtype
        })
    out.stop_gradient = True
    return out


def gaussian_random_batch_size_like(input,
                                    shape,
                                    input_dim_idx=0,
                                    output_dim_idx=0,
                                    mean=0.0,
                                    std=1.0,
                                    seed=0,
                                    dtype='float32'):
    helper = LayerHelper('gaussian_random_batch_size_like', **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type='gaussian_random_batch_size_like',
        inputs={'Input': [input]},
        outputs={'Out': [out]},
        attrs={
            'shape': list(shape),
            'input_dim_idx': input_dim_idx,
            'output_dim_idx': output_dim_idx,
            'mean': mean,
            'std': std,
            'seed': seed,
            'dtype': out.dtype
        })
    out.stop_gradient = True
    return out


from .nn import clip, clip_by_norm  # re-exported here like the reference
