"""Layers package (reference: python/paddle/fluid/layers/__init__.py)."""

from . import nn
from .nn import *
from . import io
from .io import *
from . import tensor
from .tensor import *
from . import ops
from .ops import *
from . import metric_op
from .metric_op import *
from . import sequence
from .sequence import *
from . import control_flow
from .control_flow import *
from . import learning_rate_scheduler
from .learning_rate_scheduler import *
from . import detection
from .detection import *
from . import math_op_patch  # installs Variable operator overloads

__all__ = []
__all__ += nn.__all__
__all__ += io.__all__
__all__ += tensor.__all__
__all__ += ops.__all__
__all__ += metric_op.__all__
__all__ += sequence.__all__
__all__ += control_flow.__all__
__all__ += learning_rate_scheduler.__all__
__all__ += detection.__all__
