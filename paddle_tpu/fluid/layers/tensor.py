"""Tensor layers (reference: python/paddle/fluid/layers/tensor.py)."""

import numpy as np

from .. import core
from ..framework import Variable
from ..layer_helper import LayerHelper
from ..initializer import Constant

__all__ = [
    'create_tensor', 'create_parameter', 'create_global_var', 'cast',
    'concat', 'sums', 'sum', 'assign', 'fill_constant_batch_size_like',
    'fill_constant', 'argmin', 'argmax', 'argsort', 'ones', 'zeros',
    'reverse', 'create_array', 'load',
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper('create_tensor', **locals())
    return helper.create_variable(
        name=helper.name, dtype=dtype, persistable=persistable)


def create_parameter(shape,
                     dtype,
                     name=None,
                     attr=None,
                     is_bias=False,
                     default_initializer=None):
    from ..param_attr import ParamAttr
    helper = LayerHelper('create_parameter', **locals())
    if attr is None:
        attr = ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape,
                      value,
                      dtype,
                      persistable=False,
                      force_cpu=False,
                      name=None):
    helper = LayerHelper('global_var', **locals())
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable, name=name)
    helper.set_variable_initializer(
        var, initializer=Constant(value=float(value)))
    return var


def cast(x, dtype):
    helper = LayerHelper('cast', **locals())
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = x.shape
    helper.append_op(
        type='cast',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'in_dtype': x.dtype,
               'out_dtype': out.dtype})
    return out


def concat(input, axis=0, name=None):
    from . import nn
    return nn.concat(input, axis, name)


def sums(input, out=None):
    helper = LayerHelper('sum', **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=helper.input_dtype())
        out.shape = input[0].shape
    helper.append_op(
        type='sum',
        inputs={'X': input},
        outputs={'Out': [out]})
    return out


def sum(x):
    """Elementwise sum of a list of tensors (reference layers.sum,
    auto-generated from operators/sum_op.cc)."""
    if isinstance(x, Variable):
        x = [x]
    return sums(list(x))


def create_array(dtype):
    """Create an empty LOD_TENSOR_ARRAY var (reference tensor.create_array)
    for array_write/array_read plumbing."""
    helper = LayerHelper('create_array')
    return helper.create_variable(
        name='{0}.out'.format(helper.name),
        type=core.VarDesc.VarType.LOD_TENSOR_ARRAY,
        dtype=dtype)


def load(out, file_path, load_as_fp16=None):
    """Load a saved tensor stream into ``out`` (reference layers.load /
    operators/load_op.cc)."""
    helper = LayerHelper('load')
    attrs = {'file_path': file_path}
    if load_as_fp16 is not None:
        attrs['load_as_fp16'] = load_as_fp16
    helper.append_op(
        type='load', inputs={}, outputs={'Out': [out]}, attrs=attrs)
    return out


def assign(input, output=None):
    helper = LayerHelper('assign', **locals())
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=input.dtype)
            output.shape = input.shape
        helper.append_op(
            type='assign', inputs={'X': [input]},
            outputs={'Out': [output]})
    elif isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=core.convert_np_dtype_to_dtype_(input.dtype))
            output.shape = input.shape
        helper.append_op(
            type='assign_value',
            outputs={'Out': [output]},
            attrs={
                'shape': list(input.shape),
                'dtype': output.dtype,
                'values': input
            })
    else:
        raise ValueError('assign expects Variable or numpy.ndarray')
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper('fill_constant', **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = tuple(shape)
    helper.append_op(
        type='fill_constant',
        inputs={},
        outputs={'Out': [out]},
        attrs={
            'shape': list(shape),
            'dtype': out.dtype,
            'value': float(value),
            'force_cpu': force_cpu
        })
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input,
                                  shape,
                                  dtype,
                                  value,
                                  input_dim_idx=0,
                                  output_dim_idx=0):
    helper = LayerHelper('fill_constant_batch_size_like', **locals())
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = tuple(shape)
    helper.append_op(
        type='fill_constant_batch_size_like',
        inputs={'Input': [input]},
        outputs={'Out': [out]},
        attrs={
            'shape': list(shape),
            'dtype': out.dtype,
            'value': float(value),
            'input_dim_idx': input_dim_idx,
            'output_dim_idx': output_dim_idx
        })
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(value=1.0, shape=shape, dtype=dtype)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(value=0.0, shape=shape, dtype=dtype)


def argmin(x, axis=0):
    helper = LayerHelper('argmin', **locals())
    out = helper.create_variable_for_type_inference('int64')
    helper.append_op(
        type='argmin',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'axis': axis})
    return out


def argmax(x, axis=0):
    helper = LayerHelper('argmax', **locals())
    out = helper.create_variable_for_type_inference('int64')
    helper.append_op(
        type='argmax',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'axis': axis})
    return out


def argsort(input, axis=-1, name=None):
    helper = LayerHelper('argsort', **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference('int64')
    helper.append_op(
        type='argsort',
        inputs={'X': [input]},
        outputs={'Out': [out],
                 'Indices': [ids]},
        attrs={'axis': axis})
    return out, ids


def reverse(x, axis):
    helper = LayerHelper('reverse', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        type='reverse',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'axis': axis if isinstance(axis, (list, tuple)) else [axis]})
    return out
