"""Detection layers (reference: python/paddle/fluid/layers/detection.py).

Same public surface as the reference's SSD family: prior_box,
multi_box_head, bipartite_match, target_assign, ssd_loss, detection_output,
detection_map, iou_similarity, box_coder, anchor_generator,
rpn_target_assign, polygon_box_transform.  ssd_loss lowers to ONE fused op
(ops/detection_ops.py) instead of the reference's 11-op composition — the
whole match/assign/mine pipeline stays inside a single XLA computation.
"""

from ..layer_helper import LayerHelper
from . import nn
from . import tensor

__all__ = [
    'prior_box', 'multi_box_head', 'bipartite_match', 'target_assign',
    'ssd_loss', 'detection_output', 'detection_map', 'iou_similarity',
    'box_coder', 'anchor_generator', 'rpn_target_assign',
    'polygon_box_transform', 'multiclass_nms',
    'generate_proposals', 'generate_proposal_labels',
]


def iou_similarity(x, y, name=None):
    """Pairwise IoU between box sets (reference detection.py __auto__;
    operators/detection/iou_similarity_op.cc)."""
    helper = LayerHelper('iou_similarity', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type='iou_similarity',
        inputs={'X': [x],
                'Y': [y]},
        outputs={'Out': [out]})
    return out


def box_coder(prior_box,
              prior_box_var,
              target_box,
              code_type='encode_center_size',
              box_normalized=True,
              name=None):
    """Encode/decode boxes against priors (reference detection.py __auto__;
    operators/detection/box_coder_op.cc)."""
    helper = LayerHelper('box_coder', **locals())
    out = helper.create_variable_for_type_inference(dtype=target_box.dtype)
    inputs = {'PriorBox': [prior_box], 'TargetBox': [target_box]}
    if prior_box_var is not None:
        inputs['PriorBoxVar'] = [prior_box_var]
    helper.append_op(
        type='box_coder',
        inputs=inputs,
        outputs={'OutputBox': [out]},
        attrs={'code_type': code_type,
               'box_normalized': box_normalized})
    return out


def polygon_box_transform(input, name=None):
    """(reference detection.py __auto__; polygon_box_transform_op.cc)."""
    helper = LayerHelper('polygon_box_transform', **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type='polygon_box_transform',
        inputs={'Input': [input]},
        outputs={'Output': [out]})
    return out


def bipartite_match(dist_matrix,
                    match_type=None,
                    dist_threshold=None,
                    name=None):
    """Greedy bipartite matching (reference detection.py:392;
    operators/detection/bipartite_match_op.cc)."""
    helper = LayerHelper('bipartite_match', **locals())
    match_indices = helper.create_variable_for_type_inference(dtype='int32')
    match_distance = helper.create_variable_for_type_inference(
        dtype=dist_matrix.dtype)
    helper.append_op(
        type='bipartite_match',
        inputs={'DistMat': [dist_matrix]},
        attrs={
            'match_type': match_type if match_type is not None
            else 'bipartite',
            'dist_threshold': dist_threshold if dist_threshold is not None
            else 0.5,
        },
        outputs={
            'ColToRowMatchIndices': [match_indices],
            'ColToRowMatchDist': [match_distance],
        })
    match_indices.stop_gradient = True
    match_distance.stop_gradient = True
    return match_indices, match_distance


def target_assign(input,
                  matched_indices,
                  negative_indices=None,
                  mismatch_value=None,
                  name=None):
    """Assign per-prediction targets from matched rows (reference
    detection.py:477; operators/detection/target_assign_op.cc)."""
    helper = LayerHelper('target_assign', **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    out_weight = helper.create_variable_for_type_inference(dtype='float32')
    inputs = {'X': [input], 'MatchIndices': [matched_indices]}
    if negative_indices is not None:
        inputs['NegIndices'] = [negative_indices]
    helper.append_op(
        type='target_assign',
        inputs=inputs,
        outputs={'Out': [out],
                 'OutWeight': [out_weight]},
        attrs={'mismatch_value': mismatch_value or 0})
    out.stop_gradient = True
    out_weight.stop_gradient = True
    return out, out_weight


def ssd_loss(location,
             confidence,
             gt_box,
             gt_label,
             prior_box,
             prior_box_var=None,
             background_label=0,
             overlap_threshold=0.5,
             neg_pos_ratio=3.0,
             neg_overlap=0.5,
             loc_loss_weight=1.0,
             conf_loss_weight=1.0,
             match_type='per_prediction',
             mining_type='max_negative',
             normalize=True,
             sample_size=None):
    """SSD multibox loss (reference detection.py:563).  Returns a (N, 1)
    per-image weighted loss; fused single-op lowering
    (ops/detection_ops.py ssd_loss)."""
    if mining_type not in ('max_negative', 'hard_example'):
        raise ValueError('mining_type must be max_negative or hard_example')
    if mining_type == 'hard_example' and not sample_size:
        # reference enforce (mine_hard_examples_op.cc:238-240)
        raise ValueError(
            'sample_size must be greater than zero in hard_example mode')
    helper = LayerHelper('ssd_loss', **locals())
    loss = helper.create_variable_for_type_inference(dtype=location.dtype)
    inputs = {
        'Location': [location],
        'Confidence': [confidence],
        'GtBox': [gt_box],
        'GtLabel': [gt_label],
        'PriorBox': [prior_box],
    }
    if prior_box_var is not None:
        inputs['PriorBoxVar'] = [prior_box_var]
    helper.append_op(
        type='ssd_loss',
        inputs=inputs,
        outputs={'Loss': [loss]},
        attrs={
            'background_label': background_label,
            'overlap_threshold': overlap_threshold,
            'neg_pos_ratio': neg_pos_ratio,
            'neg_overlap': neg_overlap,
            'loc_loss_weight': loc_loss_weight,
            'conf_loss_weight': conf_loss_weight,
            'match_type': match_type,
            'mining_type': mining_type,
            'normalize': normalize,
            'sample_size': sample_size or 0,
        })
    return loss


def multiclass_nms(bboxes,
                   scores,
                   score_threshold,
                   nms_top_k,
                   keep_top_k,
                   nms_threshold=0.3,
                   nms_eta=1.0,
                   background_label=0,
                   name=None):
    """Per-class NMS + cross-class top-k (reference
    operators/detection/multiclass_nms_op.cc — CPU-only kernel; host op
    here).  Output is a LoD (num_kept, 6) tensor."""
    helper = LayerHelper('multiclass_nms', **locals())
    out = helper.create_variable_for_type_inference(dtype=bboxes.dtype)
    helper.append_op(
        type='multiclass_nms',
        inputs={'BBoxes': [bboxes],
                'Scores': [scores]},
        outputs={'Out': [out]},
        attrs={
            'background_label': background_label,
            'score_threshold': score_threshold,
            'nms_top_k': nms_top_k,
            'nms_threshold': nms_threshold,
            'nms_eta': nms_eta,
            'keep_top_k': keep_top_k,
        })
    out.stop_gradient = True
    return out


def detection_output(loc,
                     scores,
                     prior_box,
                     prior_box_var,
                     background_label=0,
                     nms_threshold=0.3,
                     nms_top_k=400,
                     keep_top_k=200,
                     score_threshold=0.01,
                     nms_eta=1.0):
    """Decode + multiclass NMS (reference detection.py:186): softmax the
    scores, decode loc offsets against priors, then NMS."""
    decoded = box_coder(
        prior_box=prior_box,
        prior_box_var=prior_box_var,
        target_box=loc,
        code_type='decode_center_size')
    probs = nn.softmax(scores)
    transposed = nn.transpose(probs, perm=[0, 2, 1])  # (N, C, M)
    return multiclass_nms(
        bboxes=decoded,
        scores=transposed,
        score_threshold=score_threshold,
        nms_top_k=nms_top_k,
        keep_top_k=keep_top_k,
        nms_threshold=nms_threshold,
        nms_eta=nms_eta,
        background_label=background_label)


def detection_map(detect_res,
                  label,
                  class_num,
                  background_label=0,
                  overlap_threshold=0.3,
                  evaluate_difficult=True,
                  has_state=None,
                  input_states=None,
                  out_states=None,
                  ap_version='integral'):
    """mAP metric (reference detection.py:300; detection_map_op.cc).
    With input_states/out_states (PosCount, TruePos, FalsePos variables)
    the op accumulates tp/fp entries across batches and reports the mAP of
    the accumulated state, gated by the has_state flag variable."""
    helper = LayerHelper('detection_map', **locals())
    map_out = helper.create_variable_for_type_inference(dtype='float32')
    inputs = {'DetectRes': [detect_res], 'Label': [label]}
    if has_state is not None:
        inputs['HasState'] = [has_state]
    # input_states are NOT op inputs: the host op reads accumulated state
    # straight from the scope vars named by the Accum* outputs, so the
    # executor never treats them as jit state needing initialization
    outputs = {'MAP': [map_out]}
    if out_states is not None:
        outputs['AccumPosCount'] = [out_states[0]]
        outputs['AccumTruePos'] = [out_states[1]]
        outputs['AccumFalsePos'] = [out_states[2]]
    elif input_states is not None:
        # reference semantics: states update in place when only inputs given
        outputs['AccumPosCount'] = [input_states[0]]
        outputs['AccumTruePos'] = [input_states[1]]
        outputs['AccumFalsePos'] = [input_states[2]]
    helper.append_op(
        type='detection_map',
        inputs=inputs,
        outputs=outputs,
        attrs={
            'overlap_threshold': overlap_threshold,
            'evaluate_difficult': evaluate_difficult,
            'ap_type': ap_version,
            'class_num': class_num,
            'background_label': background_label,
        })
    map_out.stop_gradient = True
    return map_out


def prior_box(input,
              image,
              min_sizes,
              max_sizes=None,
              aspect_ratios=[1.],
              variance=[0.1, 0.1, 0.2, 0.2],
              flip=False,
              clip=False,
              steps=[0.0, 0.0],
              offset=0.5,
              name=None):
    """SSD prior boxes for one feature map (reference detection.py:801;
    operators/detection/prior_box_op.cc)."""
    helper = LayerHelper('prior_box', **locals())

    def to_list(v):
        if v is None:
            return []
        return list(v) if isinstance(v, (list, tuple)) else [v]

    box = helper.create_variable_for_type_inference(dtype=input.dtype)
    var = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type='prior_box',
        inputs={'Input': [input],
                'Image': [image]},
        outputs={'Boxes': [box],
                 'Variances': [var]},
        attrs={
            'min_sizes': [float(v) for v in to_list(min_sizes)],
            'max_sizes': [float(v) for v in to_list(max_sizes)],
            'aspect_ratios': [float(v) for v in to_list(aspect_ratios)],
            'variances': [float(v) for v in variance],
            'flip': flip,
            'clip': clip,
            'step_w': float(steps[0]),
            'step_h': float(steps[1]),
            'offset': offset,
        })
    box.stop_gradient = True
    var.stop_gradient = True
    return box, var


def anchor_generator(input,
                     anchor_sizes=None,
                     aspect_ratios=None,
                     variance=[0.1, 0.1, 0.2, 0.2],
                     stride=None,
                     offset=0.5,
                     name=None):
    """RPN anchors for one feature map (reference detection.py:1167;
    operators/detection/anchor_generator_op.cc)."""
    helper = LayerHelper('anchor_generator', **locals())
    anchor = helper.create_variable_for_type_inference(dtype=input.dtype)
    var = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type='anchor_generator',
        inputs={'Input': [input]},
        outputs={'Anchors': [anchor],
                 'Variances': [var]},
        attrs={
            'anchor_sizes': [float(v) for v in anchor_sizes],
            'aspect_ratios': [float(v) for v in aspect_ratios],
            'variances': [float(v) for v in variance],
            'stride': [float(v) for v in stride],
            'offset': offset,
        })
    anchor.stop_gradient = True
    var.stop_gradient = True
    return anchor, var


def rpn_target_assign(loc,
                      scores,
                      anchor_box,
                      gt_box,
                      rpn_batch_size_per_im=256,
                      fg_fraction=0.25,
                      rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3,
                      fix_seed=False,
                      seed=0):
    """Sample anchors for RPN training (reference detection.py:58;
    rpn_target_assign_op.cc).  Returns (predicted_scores,
    predicted_location, target_label, target_bbox) index tensors."""
    helper = LayerHelper('rpn_target_assign', **locals())
    iou = iou_similarity(x=gt_box, y=anchor_box)
    loc_index = helper.create_variable_for_type_inference(dtype='int64')
    score_index = helper.create_variable_for_type_inference(dtype='int64')
    target_label = helper.create_variable_for_type_inference(dtype='int64')
    target_bbox = helper.create_variable_for_type_inference(
        dtype=anchor_box.dtype)
    helper.append_op(
        type='rpn_target_assign',
        inputs={'DistMat': [iou],
                'Anchor': [anchor_box],
                'GtBox': [gt_box]},
        outputs={
            'LocationIndex': [loc_index],
            'ScoreIndex': [score_index],
            'TargetLabel': [target_label],
            'TargetBBox': [target_bbox],
        },
        attrs={
            'rpn_batch_size_per_im': rpn_batch_size_per_im,
            'rpn_fg_fraction': fg_fraction,
            'rpn_positive_overlap': rpn_positive_overlap,
            'rpn_negative_overlap': rpn_negative_overlap,
            'fix_seed': fix_seed,
            'seed': seed,
        })
    for v in (loc_index, score_index, target_label, target_bbox):
        v.stop_gradient = True
    return loc_index, score_index, target_label, target_bbox


def multi_box_head(inputs,
                   image,
                   base_size,
                   num_classes,
                   aspect_ratios,
                   min_ratio=None,
                   max_ratio=None,
                   min_sizes=None,
                   max_sizes=None,
                   steps=None,
                   step_w=None,
                   step_h=None,
                   offset=0.5,
                   variance=[0.1, 0.1, 0.2, 0.2],
                   flip=True,
                   clip=False,
                   kernel_size=1,
                   pad=0,
                   stride=1,
                   name=None):
    """SSD detection head over multiple feature maps (reference
    detection.py:921): per-map conv heads for loc/conf + per-map priors,
    all flattened and concatenated.  Returns (mbox_locs, mbox_confs,
    prior_boxes, variances)."""
    helper = LayerHelper('multi_box_head', **locals())
    num_layer = len(inputs)

    if min_sizes is None:
        # reference: ratios interpolated between min_ratio and max_ratio
        assert num_layer >= 2, 'multi_box_head needs >= 2 inputs'
        min_sizes = []
        max_sizes = []
        step = int(
            (max_ratio - min_ratio) / (num_layer - 2)) if num_layer > 2 else 0
        min_sizes = [base_size * 0.1]
        max_sizes = [base_size * 0.2]
        for ratio in range(min_ratio, max_ratio + 1, step or 1):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = min_sizes[:num_layer]
        max_sizes = max_sizes[:num_layer]

    mbox_locs = []
    mbox_confs = []
    boxes = []
    variances = []
    for i, x in enumerate(inputs):
        min_size = min_sizes[i]
        max_size = max_sizes[i] if max_sizes else None
        if not isinstance(min_size, (list, tuple)):
            min_size = [min_size]
        if max_size is not None and not isinstance(max_size, (list, tuple)):
            max_size = [max_size]
        ar = aspect_ratios[i]
        if not isinstance(ar, (list, tuple)):
            ar = [ar]
        if steps is not None:
            step_pair = steps[i] if isinstance(steps[i],
                                               (list,
                                                tuple)) else [steps[i]] * 2
        else:
            step_pair = [step_w[i] if step_w else 0.0,
                         step_h[i] if step_h else 0.0]
        box, var = prior_box(x, image, min_size, max_size, ar, variance,
                             flip, clip, step_pair, offset)
        boxes.append(box)
        variances.append(var)
        # priors per cell — mirror of ops/detection_ops.py _prior_box
        from ...ops.detection_ops import _expand_aspect_ratios
        ars = _expand_aspect_ratios(ar, flip)
        num_boxes = len(ars) * len(min_size) + len(max_size or [])

        loc = nn.conv2d(
            input=x,
            num_filters=num_boxes * 4,
            filter_size=kernel_size,
            padding=pad,
            stride=stride)
        loc = nn.transpose(loc, perm=[0, 2, 3, 1])
        loc = nn.reshape(loc, shape=[0, -1, 4])
        mbox_locs.append(loc)

        conf = nn.conv2d(
            input=x,
            num_filters=num_boxes * num_classes,
            filter_size=kernel_size,
            padding=pad,
            stride=stride)
        conf = nn.transpose(conf, perm=[0, 2, 3, 1])
        conf = nn.reshape(conf, shape=[0, -1, num_classes])
        mbox_confs.append(conf)

        boxes[-1] = nn.reshape(box, shape=[-1, 4])
        variances[-1] = nn.reshape(var, shape=[-1, 4])

    mbox_locs_concat = tensor.concat(mbox_locs, axis=1)
    mbox_confs_concat = tensor.concat(mbox_confs, axis=1)
    box_concat = tensor.concat(boxes, axis=0)
    var_concat = tensor.concat(variances, axis=0)
    for v in (box_concat, var_concat):
        v.stop_gradient = True
    return mbox_locs_concat, mbox_confs_concat, box_concat, var_concat


def generate_proposals(scores,
                       bbox_deltas,
                       im_info,
                       anchors,
                       variances,
                       pre_nms_top_n=6000,
                       post_nms_top_n=1000,
                       nms_thresh=0.5,
                       min_size=0.1,
                       eta=1.0,
                       name=None):
    """RPN proposal generation (reference detection.py:1317;
    generate_proposals_op.cc).  Returns (rpn_rois, rpn_roi_probs) LoD."""
    helper = LayerHelper('generate_proposals', **locals())
    rpn_rois = helper.create_variable_for_type_inference(
        dtype=bbox_deltas.dtype)
    rpn_roi_probs = helper.create_variable_for_type_inference(
        dtype=scores.dtype)
    helper.append_op(
        type='generate_proposals',
        inputs={
            'Scores': [scores],
            'BboxDeltas': [bbox_deltas],
            'ImInfo': [im_info],
            'Anchors': [anchors],
            'Variances': [variances],
        },
        outputs={'RpnRois': [rpn_rois],
                 'RpnRoiProbs': [rpn_roi_probs]},
        attrs={
            'pre_nms_topN': pre_nms_top_n,
            'post_nms_topN': post_nms_top_n,
            'nms_thresh': nms_thresh,
            'min_size': min_size,
            'eta': eta,
        })
    rpn_rois.stop_gradient = True
    rpn_roi_probs.stop_gradient = True
    return rpn_rois, rpn_roi_probs


def generate_proposal_labels(rpn_rois,
                             gt_classes,
                             is_crowd,
                             gt_boxes,
                             im_info,
                             batch_size_per_im=256,
                             fg_fraction=0.25,
                             fg_thresh=0.25,
                             bg_thresh_hi=0.5,
                             bg_thresh_lo=0.0,
                             bbox_reg_weights=[0.1, 0.1, 0.2, 0.2],
                             class_nums=None,
                             use_random=True):
    """Second-stage RoI sampling (reference detection.py:1259;
    generate_proposal_labels_op.cc).  Returns (rois, labels_int32,
    bbox_targets, bbox_inside_weights, bbox_outside_weights)."""
    helper = LayerHelper('generate_proposal_labels', **locals())
    rois = helper.create_variable_for_type_inference(dtype=rpn_rois.dtype)
    labels_int32 = helper.create_variable_for_type_inference(dtype='int32')
    bbox_targets = helper.create_variable_for_type_inference(
        dtype=rpn_rois.dtype)
    bbox_inside_weights = helper.create_variable_for_type_inference(
        dtype=rpn_rois.dtype)
    bbox_outside_weights = helper.create_variable_for_type_inference(
        dtype=rpn_rois.dtype)
    helper.append_op(
        type='generate_proposal_labels',
        inputs={
            'RpnRois': [rpn_rois],
            'GtClasses': [gt_classes],
            'IsCrowd': [is_crowd],
            'GtBoxes': [gt_boxes],
            'ImInfo': [im_info],
        },
        outputs={
            'Rois': [rois],
            'LabelsInt32': [labels_int32],
            'BboxTargets': [bbox_targets],
            'BboxInsideWeights': [bbox_inside_weights],
            'BboxOutsideWeights': [bbox_outside_weights],
        },
        attrs={
            'batch_size_per_im': batch_size_per_im,
            'fg_fraction': fg_fraction,
            'fg_thresh': fg_thresh,
            'bg_thresh_hi': bg_thresh_hi,
            'bg_thresh_lo': bg_thresh_lo,
            'bbox_reg_weights': bbox_reg_weights,
            'class_nums': class_nums or 81,
            'fix_seed': not use_random,
        })
    for v in (rois, labels_int32, bbox_targets, bbox_inside_weights,
              bbox_outside_weights):
        v.stop_gradient = True
    return (rois, labels_int32, bbox_targets, bbox_inside_weights,
            bbox_outside_weights)
