"""Metric layers (reference: python/paddle/fluid/layers/metric_op.py)."""

from ..layer_helper import LayerHelper
from ..framework import Variable

__all__ = ['accuracy', 'auc', 'chunk_eval', 'precision_recall',
           'positive_negative_pair']


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy (reference metric_op.py:29; operators/accuracy_op.cc)."""
    helper = LayerHelper('accuracy', **locals())
    topk_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    topk_indices = helper.create_variable_for_type_inference(dtype='int64')
    helper.append_op(
        type='top_k',
        inputs={'X': [input]},
        outputs={'Out': [topk_out],
                 'Indices': [topk_indices]},
        attrs={'k': k})
    acc_out = helper.create_variable_for_type_inference(dtype='float32')
    if correct is None:
        correct = helper.create_variable_for_type_inference(dtype='int64')
    if total is None:
        total = helper.create_variable_for_type_inference(dtype='int64')
    helper.append_op(
        type='accuracy',
        inputs={
            'Out': [topk_out],
            'Indices': [topk_indices],
            'Label': [label]
        },
        outputs={
            'Accuracy': [acc_out],
            'Correct': [correct],
            'Total': [total]
        })
    acc_out.stop_gradient = True
    return acc_out


def auc(input, label, curve='ROC', num_thresholds=200, topk=1):
    """Batch AUC (reference metric_op.py auc; operators/auc_op.cc)."""
    helper = LayerHelper('auc', **locals())
    auc_out = helper.create_variable_for_type_inference(dtype='float64')
    helper.append_op(
        type='auc',
        inputs={'Predict': [input],
                'Label': [label]},
        outputs={'AUC': [auc_out]},
        attrs={'curve': curve,
               'num_thresholds': num_thresholds})
    auc_out.stop_gradient = True
    return auc_out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    """Chunk detection precision/recall/F1 over tagged sequences
    (reference layers/nn.py chunk_eval; operators/chunk_eval_op.cc).
    Returns (precision, recall, f1, num_infer, num_label, num_correct)."""
    helper = LayerHelper('chunk_eval', **locals())
    precision = helper.create_variable_for_type_inference('float32')
    recall = helper.create_variable_for_type_inference('float32')
    f1_score = helper.create_variable_for_type_inference('float32')
    num_infer_chunks = helper.create_variable_for_type_inference('int64')
    num_label_chunks = helper.create_variable_for_type_inference('int64')
    num_correct_chunks = helper.create_variable_for_type_inference('int64')
    helper.append_op(
        type='chunk_eval',
        inputs={'Inference': [input],
                'Label': [label]},
        outputs={
            'Precision': [precision],
            'Recall': [recall],
            'F1-Score': [f1_score],
            'NumInferChunks': [num_infer_chunks],
            'NumLabelChunks': [num_label_chunks],
            'NumCorrectChunks': [num_correct_chunks],
        },
        attrs={
            'chunk_scheme': chunk_scheme,
            'num_chunk_types': num_chunk_types,
            'excluded_chunk_types': excluded_chunk_types or [],
        })
    return (precision, recall, f1_score, num_infer_chunks,
            num_label_chunks, num_correct_chunks)


def precision_recall(input, label, class_number=None):
    """Per-class precision/recall/F1 batch metrics (reference
    operators/precision_recall_op.cc).  ``input`` is the probability
    matrix; the op consumes the argmax indices."""
    helper = LayerHelper('precision_recall', **locals())
    cls = class_number
    if cls is None:
        shape = getattr(input, 'shape', None)
        if not shape or len(shape) < 2 or shape[-1] is None or \
                int(shape[-1]) < 0:
            raise ValueError(
                'precision_recall: cannot infer class_number from input '
                'shape %r - pass class_number explicitly' % (shape, ))
        cls = int(shape[-1])
    from .nn import topk
    _, idx = topk(input, 1)
    batch_metrics = helper.create_variable_for_type_inference('float32')
    batch_metrics.shape = (3, )
    helper.append_op(
        type='precision_recall',
        inputs={'Indices': [idx],
                'Labels': [label]},
        outputs={'BatchMetrics': [batch_metrics]},
        attrs={'class_number': int(cls)})
    return batch_metrics


def positive_negative_pair(score, label, query_id):
    """Ranking pair agreement counts per query (reference
    operators/positive_negative_pair_op.cc).
    Returns (positive, negative, neutral) pair counts."""
    helper = LayerHelper('positive_negative_pair', **locals())
    pos = helper.create_variable_for_type_inference('float32')
    neg = helper.create_variable_for_type_inference('float32')
    neu = helper.create_variable_for_type_inference('float32')
    helper.append_op(
        type='positive_negative_pair',
        inputs={'Score': [score],
                'Label': [label],
                'QueryID': [query_id]},
        outputs={'PositivePair': [pos],
                 'NegativePair': [neg],
                 'NeutralPair': [neu]})
    return pos, neg, neu
