"""Learning-rate decay schedules
(reference: python/paddle/fluid/layers/learning_rate_scheduler.py).

Each schedule appends ops computing the decayed LR from the global step
counter — graph ops, so the whole schedule compiles into the training step.
"""

import math

from ..framework import default_main_program
from ..initializer import Constant
from ..layer_helper import LayerHelper
from . import nn
from . import ops
from . import tensor
from . import control_flow

__all__ = [
    'exponential_decay', 'natural_exp_decay', 'inverse_time_decay',
    'polynomial_decay', 'piecewise_decay', 'noam_decay', 'append_LARS',
]


def _decay_step_counter(begin=0):
    global_step = nn.autoincreased_step_counter(
        counter_name='@LR_DECAY_COUNTER@', begin=begin, step=1)
    global_step = tensor.cast(global_step, 'float32')
    return global_step


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (reference learning_rate_scheduler.py:36)."""
    global_step = _decay_step_counter(1)
    a = ops.pow(global_step, factor=-0.5)
    b = ops.scale(global_step, scale=warmup_steps**-1.5)
    lr_value = ops.scale(
        ops.elementwise_min(a, b), scale=d_model**-0.5)
    return lr_value


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * decay_rate ^ (step / decay_steps)."""
    global_step = _decay_step_counter()
    div_res = ops.scale(global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    # rate^x = exp(x * ln rate)
    decayed = ops.exp(ops.scale(div_res, scale=math.log(decay_rate)))
    return ops.scale(decayed, scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * exp(-decay_rate * step / decay_steps)."""
    global_step = _decay_step_counter()
    div_res = ops.scale(global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    decayed = ops.exp(ops.scale(div_res, scale=-float(decay_rate)))
    return ops.scale(decayed, scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    """lr / (1 + decay_rate * step / decay_steps)."""
    global_step = _decay_step_counter()
    div_res = ops.scale(global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    denom = ops.scale(div_res, scale=float(decay_rate), bias=1.0)
    one = tensor.fill_constant(shape=[1], dtype='float32',
                               value=float(learning_rate))
    return ops.elementwise_div(one, denom)


def polynomial_decay(learning_rate,
                     decay_steps,
                     end_learning_rate=0.0001,
                     power=1.0,
                     cycle=False):
    """(lr - end) * (1 - step/decay_steps)^power + end."""
    global_step = _decay_step_counter()
    if cycle:
        div_res = ops.ceil(
            ops.scale(global_step, scale=1.0 / decay_steps))
        # when step == 0, div_res should be 1
        zero = tensor.fill_constant(shape=[1], dtype='float32', value=0.0)
        one = tensor.fill_constant(shape=[1], dtype='float32', value=1.0)
        div_res = ops.elementwise_max(div_res, one)
        decay_steps_var = ops.scale(div_res, scale=float(decay_steps))
        ratio = ops.elementwise_div(global_step, decay_steps_var)
    else:
        capped = ops.elementwise_min(
            global_step,
            tensor.fill_constant(
                shape=[1], dtype='float32', value=float(decay_steps)))
        ratio = ops.scale(capped, scale=1.0 / decay_steps)
    base = ops.scale(ratio, scale=-1.0, bias=1.0)
    powed = ops.pow(base, factor=float(power))
    return ops.scale(
        powed,
        scale=float(learning_rate) - float(end_learning_rate),
        bias=0.0) + float(end_learning_rate)


def piecewise_decay(boundaries, values):
    """Step-wise constant LR (reference learning_rate_scheduler.py
    piecewise_decay) — lowered as a chain of selects instead of a Switch
    block: lr = values[i] for boundaries[i-1] <= step < boundaries[i]."""
    if len(values) - len(boundaries) != 1:
        raise ValueError('len(values) must be len(boundaries) + 1')
    global_step = _decay_step_counter()
    lr = tensor.fill_constant(
        shape=[1], dtype='float32', value=float(values[-1]))
    # fold from the last boundary backwards with where-selects
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        boundary = tensor.fill_constant(
            shape=[1], dtype='float32', value=float(b))
        cond = control_flow.less_than(global_step, boundary)
        vconst = tensor.fill_constant(
            shape=[1], dtype='float32', value=float(v))
        helper = LayerHelper('piecewise_select')
        out = helper.create_variable_for_type_inference('float32')
        helper.append_op(
            type='where_select',
            inputs={'Cond': [cond],
                    'X': [vconst],
                    'Y': [lr]},
            outputs={'Out': [out]})
        lr = out
    return lr


def append_LARS(params_grads, learning_rate, weight_decay):
    """LARS per-layer scaling (reference learning_rate_scheduler.py:312)."""

    def _balanced_weight(param_norm, grad_norm):
        if weight_decay == 1.0:
            return grad_norm + param_norm
        return grad_norm + weight_decay * param_norm

    for param, grad in params_grads:
        param_lr = param.optimize_attr['learning_rate']
        param_norm = ops.sqrt(nn.reduce_sum(input=ops.square(param)))
        grad_norm = ops.sqrt(nn.reduce_sum(input=ops.square(grad)))
        if type(param_lr) == float and param_lr == 1.0:
            decayed_lr = learning_rate * param_norm / _balanced_weight(
                param_norm, grad_norm)
        else:
            decayed_lr = learning_rate * param_lr * param_norm / \
                _balanced_weight(param_norm, grad_norm)
        param.optimize_attr['learning_rate'] = decayed_lr
