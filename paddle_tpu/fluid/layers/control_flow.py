"""Control-flow layers (reference: python/paddle/fluid/layers/control_flow.py).

The reference's While/StaticRNN/DynamicRNN re-enter the C++ Executor per
step with step-scopes (operators/while_op.cc:36-66, recurrent_op.cc:47-135).
Here each construct builds a sub-block that lowers ONCE into a functional
``lax.scan`` / ``lax.while_loop`` — compiler-friendly control flow with
explicit carried state, per SURVEY §7 guiding decision 4.
"""

import contextlib

from .. import core
from .. import unique_name
from ..framework import Variable, Operator, default_main_program
from ..layer_helper import LayerHelper
from ..initializer import Constant
from .tensor import fill_constant

__all__ = [
    'While', 'StaticRNN', 'DynamicRNN', 'increment', 'array_write',
    'array_read', 'array_length', 'less_than', 'equal', 'Switch', 'IfElse',
    'zeros_like', 'Print', 'is_empty', 'lod_rank_table',
    'reorder_lod_tensor_by_rank', 'split_lod_tensor', 'merge_lod_tensor',
]


def less_than(x, y, cond=None, **ignored):
    helper = LayerHelper('less_than', **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype='bool')
        cond.stop_gradient = True
    helper.append_op(
        type='less_than',
        inputs={'X': [x],
                'Y': [y]},
        outputs={'Out': [cond]})
    return cond


def equal(x, y, cond=None, **ignored):
    helper = LayerHelper('equal', **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype='bool')
        cond.stop_gradient = True
    helper.append_op(
        type='equal', inputs={'X': [x],
                              'Y': [y]}, outputs={'Out': [cond]})
    return cond


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper('increment', **locals())
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type='increment',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'step': float(value)})
    return out


def zeros_like(x, out=None):
    helper = LayerHelper('zeros_like', **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type='fill_zeros_like', inputs={'X': [x]}, outputs={'Out': [out]})
    return out


def array_write(x, i, array=None):
    """Write x into a tensor array at index i (reference control_flow.py
    array_write; operators/tensor_array_read_write.cc)."""
    helper = LayerHelper('array_write', **locals())
    if array is None:
        array = helper.create_variable(
            name='{0}.out'.format(helper.name),
            type=core.VarDesc.VarType.LOD_TENSOR_ARRAY,
            dtype=x.dtype)
    helper.append_op(
        type='write_to_array',
        inputs={'X': [x],
                'I': [i]},
        outputs={'Out': [array]},
        # correlates this op with its backward so trace-time concrete
        # indices survive in-place index rewrites (ops/control_flow_ops.py)
        attrs={'_array_op_id': unique_name.generate('awrite')})
    return array


def array_read(array, i):
    helper = LayerHelper('array_read', **locals())
    out = helper.create_variable_for_type_inference(dtype=array.dtype)
    helper.append_op(
        type='read_from_array',
        inputs={'X': [array],
                'I': [i]},
        outputs={'Out': [out]},
        attrs={'_array_op_id': unique_name.generate('aread')})
    return out


def array_length(array):
    helper = LayerHelper('array_length', **locals())
    tmp = helper.create_variable_for_type_inference(
        dtype='int64', stop_gradient=True)
    helper.append_op(
        type='lod_array_length',
        inputs={'X': [array]},
        outputs={'Out': [tmp]})
    return tmp


def _external_reads(sub_block, exclude=()):
    """Vars a sub-block reads from enclosing blocks (weights, globals).
    Declared as explicit op inputs so the executor threads them into the
    compiled state and backward can produce their gradients — the analog
    of the reference while_op's X input list."""
    exclude = set(exclude)
    local_writes = set()
    names = []
    for op in sub_block.ops:
        for n in op.input_arg_names:
            if (n not in sub_block.vars and n not in local_writes and
                    n not in exclude and n not in names):
                names.append(n)
        for n in op.output_arg_names:
            local_writes.add(n)
    return names


class BlockGuard(object):
    def __init__(self, main_program):
        self.main_program = main_program

    def __enter__(self):
        self.main_program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.main_program.rollback()
        return exc_type is None


class While(object):
    """while (cond) { sub-block } lowered to lax.while_loop
    (reference control_flow.py:655).  Carried state = every parent var the
    sub-block writes; tensor-array appends are supported when the loop
    runs a statically-bounded counter (the common fluid pattern).

    ``max_trip_count`` makes the loop differentiable (reference
    while_grad, operators/while_op.cc:36): the loop lowers to a bounded
    masked ``lax.scan`` whose residuals XLA stacks for the backward pass
    — the functional replacement for the reference's step-scope stack.
    Carried tensor arrays are preallocated to the trip bound."""

    def __init__(self, cond, is_test=False, name=None, max_trip_count=0):
        self.helper = LayerHelper('while', name=name)
        if cond.dtype != core.VarDesc.VarType.BOOL:
            raise TypeError('condition should be a bool variable')
        self.cond_var = cond
        self.max_trip_count = int(max_trip_count or 0)

    @contextlib.contextmanager
    def block(self):
        main_program = self.helper.main_program
        parent_idx = main_program.current_block_idx
        sub_block = main_program.create_block()
        try:
            yield
        finally:
            main_program.rollback()
        parent_block = main_program.block(parent_idx)
        # vars the body writes that exist in an enclosing block = loop state
        inner = sub_block
        mod_names = []
        for op in inner.ops:
            for n in op.output_arg_names:
                if n not in inner.vars and n not in mod_names:
                    mod_names.append(n)
        # snapshot every carried var's pre-loop value under a fresh name:
        # the functional env holds one value per name, so without this the
        # backward pass would recompute the loop from FINAL values (the
        # reference keeps initials alive in the parent scope instead)
        carry_names = [self.cond_var.name] + [
            n for n in mod_names if n != self.cond_var.name
        ]
        init_names = []
        for n in carry_names:
            src = parent_block._find_var_recursive(n)
            kwargs = {'name': unique_name.generate(n + '@WHILE_INIT')}
            if src is not None:
                kwargs['dtype'] = src.dtype
                kwargs['type'] = src.type
            snap = parent_block.create_var(**kwargs)
            parent_block.append_op(
                type='assign', inputs={'X': [n]}, outputs={'Out': [snap.name]},
                attrs={})
            init_names.append(snap.name)
        parent_block.append_op(
            type='while',
            inputs={
                'Condition': [self.cond_var],
                # carried vars are covered by Init snapshots; listing them
                # in X too would add a dead (final-value) grad path
                'X': _external_reads(sub_block, carry_names),
                'Init': init_names,
            },
            outputs={'Out': mod_names},
            attrs={'sub_block': sub_block,
                   'carry_names': carry_names,
                   'max_trip_count': self.max_trip_count})


class StaticRNN(object):
    """Uniform-length RNN over time-major slices
    (reference control_flow.py:430; operators/recurrent_op.cc).  Lowered to
    one lax.scan."""

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper('static_rnn', name=name)
        self.memories = {}  # in-block mem var name -> (init name, update name)
        self.inputs = []  # (seq var, in-block var)
        self.outputs = []
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.sub_block = None
        self.parent_idx = None

    @contextlib.contextmanager
    def step(self):
        main_program = self.helper.main_program
        self.parent_idx = main_program.current_block_idx
        self.sub_block = main_program.create_block()
        self.status = StaticRNN.IN_RNN_BLOCK
        try:
            yield
        finally:
            main_program.rollback()
            self.status = StaticRNN.AFTER_RNN_BLOCK
            self._complete_op()

    def _assert_in_rnn_block_(self, method):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError('You must invoke {0} in rnn.step()'.format(
                method))

    def memory(self,
               init=None,
               shape=None,
               batch_ref=None,
               init_value=0.0,
               init_batch_dim_idx=0,
               ref_batch_dim_idx=1):
        self._assert_in_rnn_block_('memory')
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError(
                    'if init is None, memory at least need shape and '
                    'batch_ref')
            parent_block = self.helper.main_program.block(self.parent_idx)
            ref_name = batch_ref.name
            dim_idx = ref_batch_dim_idx
            # batch_ref may be an in-block step input; the init op lives in
            # the parent block, so reference the parent sequence var
            # instead (its batch dim is axis 1, time-major)
            for seq_name, step_name in self.inputs:
                if step_name == ref_name:
                    ref_name = seq_name
                    dim_idx = ref_batch_dim_idx + 1
                    break
            init = parent_block.create_var(
                name='{}.init.{}'.format(self.helper.name,
                                         len(self.memories)),
                dtype='float32',
                shape=[-1] + list(shape))
            parent_block.append_op(
                type='fill_constant_batch_size_like',
                inputs={'Input': [ref_name]},
                outputs={'Out': [init]},
                attrs={
                    'shape': [-1] + list(shape),
                    'value': float(init_value),
                    'input_dim_idx': dim_idx,
                    'dtype': init.dtype,
                })
        mem = self.sub_block.create_var(
            name='{}.mem.{}'.format(self.helper.name, len(self.memories)),
            dtype=init.dtype,
            shape=init.shape)
        self.memories[mem.name] = [init.name, None]
        return mem

    def step_input(self, x):
        # StaticRNN is time-major: x is [T, B, ...], each step sees [B, ...]
        self._assert_in_rnn_block_('step_input')
        ipt = self.sub_block.create_var(
            name=x.name + '@step', dtype=x.dtype, shape=tuple(x.shape[1:]))
        self.inputs.append((x.name, ipt.name))
        return ipt

    def step_output(self, o):
        self._assert_in_rnn_block_('step_output')
        self.outputs.append(o.name)

    def output(self, *outputs):
        for each in outputs:
            self.step_output(each)

    def update_memory(self, mem, var):
        self._assert_in_rnn_block_('update_memory')
        if mem.name not in self.memories:
            raise ValueError('unknown memory %s' % mem.name)
        self.memories[mem.name][1] = var.name

    def _complete_op(self):
        main_program = self.helper.main_program
        parent_block = main_program.block(self.parent_idx)
        out_vars = []
        for name in self.outputs:
            step_var = self.sub_block._find_var_recursive(name)
            ov = parent_block.create_var(
                name=name + '@rnn_out',
                dtype=step_var.dtype if step_var is not None else 'float32')
            out_vars.append(ov)
        self._out_vars = out_vars
        exclude = [i for _, i in self.inputs] + list(self.memories.keys())
        parent_block.append_op(
            type='recurrent',
            inputs={
                'SeqInputs': [n for n, _ in self.inputs],
                'MemInits': [v[0] for v in self.memories.values()],
                'ClosureInputs': _external_reads(self.sub_block, exclude),
            },
            outputs={'Out': out_vars},
            attrs={
                'sub_block': self.sub_block,
                'step_input_names': [i for _, i in self.inputs],
                'mem_names': list(self.memories.keys()),
                'mem_update_names': [v[1] for v in self.memories.values()],
                'output_names': list(self.outputs),
                'time_major': True,
                'masked': False,
            })

    def __call__(self, *args, **kwargs):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise ValueError('RNN output can only be retrieved after the '
                             'step block')
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars


class DynamicRNN(object):
    """Variable-length RNN (reference control_flow.py:1542).

    The reference sorts sequences by length into a LoDRankTable, shards
    timesteps into a LoDTensorArray, and drives a while-op with shrinking
    batch (lod_rank_table_op, shrink_rnn_memory_op).  Lowered here as one
    masked lax.scan over the padded batch — same results, no reordering."""

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper('dynamic_rnn', name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self.memories = {}
        self.inputs = []
        self.static_inputs = []
        self.outputs = []
        self.sub_block = None
        self.parent_idx = None

    @contextlib.contextmanager
    def block(self):
        main_program = self.helper.main_program
        self.parent_idx = main_program.current_block_idx
        self.sub_block = main_program.create_block()
        self.status = DynamicRNN.IN_RNN
        try:
            yield
        finally:
            main_program.rollback()
            self.status = DynamicRNN.AFTER_RNN
            self._complete_op()

    def step_input(self, x, level=0):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError('step_input must be called in block()')
        # x's desc shape is the concatenated LoD form (total, ...), which is
        # already time-free: the per-step batch slice has the same rank
        ipt = self.sub_block.create_var(
            name=x.name + '@step', dtype=x.dtype, shape=tuple(x.shape))
        self.inputs.append((x.name, ipt.name))
        return ipt

    def static_input(self, x):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError('static_input must be called in block()')
        # visible unchanged every step (closure)
        self.static_inputs.append(x.name)
        return x

    def memory(self,
               init=None,
               shape=None,
               value=0.0,
               need_reorder=False,
               dtype='float32'):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError('memory must be called in block()')
        if init is None:
            if shape is None:
                raise ValueError('memory needs init or shape')
            parent_block = self.helper.main_program.block(self.parent_idx)
            first_seq = self.inputs[0][0] if self.inputs else None
            init = parent_block.create_var(
                name='{}.mem_init.{}'.format(self.helper.name,
                                             len(self.memories)),
                dtype=dtype,
                shape=[-1] + list(shape))
            parent_block.append_op(
                type='fill_constant_batch_size_like',
                inputs={'Input': [first_seq]},
                outputs={'Out': [init]},
                attrs={
                    'shape': [-1] + list(shape),
                    'value': float(value),
                    'dtype': init.dtype,
                })
        mem = self.sub_block.create_var(
            name='{}.mem.{}'.format(self.helper.name, len(self.memories)),
            dtype=init.dtype,
            shape=init.shape)
        self.memories[mem.name] = [init.name, None]
        return mem

    def update_memory(self, ex_mem, new_mem):
        if ex_mem.name not in self.memories:
            raise ValueError('unknown memory %s' % ex_mem.name)
        self.memories[ex_mem.name][1] = new_mem.name

    def output(self, *outputs):
        for o in outputs:
            self.outputs.append(o.name)

    def _complete_op(self):
        main_program = self.helper.main_program
        parent_block = main_program.block(self.parent_idx)
        out_vars = []
        for name in self.outputs:
            step_var = self.sub_block._find_var_recursive(name)
            ov = parent_block.create_var(
                name=name + '@rnn_out',
                dtype=step_var.dtype if step_var is not None else 'float32',
                lod_level=1)
            if step_var is not None and step_var.shape:
                # per-step [B, ...] stacks to a sequence [N, ...]; keep the
                # feature dims so downstream fc sizes its weight correctly
                ov.shape = (-1, ) + tuple(step_var.shape[1:])
            out_vars.append(ov)
        self._out_vars = out_vars
        exclude = [i for _, i in self.inputs] + list(self.memories.keys())
        parent_block.append_op(
            type='recurrent',
            inputs={
                'SeqInputs': [n for n, _ in self.inputs],
                'MemInits': [v[0] for v in self.memories.values()],
                'StaticInputs': list(self.static_inputs),
                'ClosureInputs': _external_reads(
                    self.sub_block, exclude + list(self.static_inputs)),
            },
            outputs={'Out': out_vars},
            attrs={
                'sub_block': self.sub_block,
                'step_input_names': [i for _, i in self.inputs],
                'mem_names': list(self.memories.keys()),
                'mem_update_names': [v[1] for v in self.memories.values()],
                'output_names': list(self.outputs),
                'time_major': False,
                'masked': True,
            })

    def __call__(self, *args, **kwargs):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError(
                'Output of the dynamic RNN can only be visited outside the '
                'rnn block')
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars


class Switch(object):
    """Piecewise case construct (reference control_flow.py:1286).  Each
    case's sub-block is lowered and blended with jnp.where — all branches
    execute (XLA-friendly select), semantics match when branches are
    side-effect-free (the LR-scheduler use)."""

    def __init__(self, name=None):
        self.helper = LayerHelper('switch', name=name)
        self.cases = []  # (cond name or None, sub_block)
        self.parent_idx = None

    @contextlib.contextmanager
    def case(self, condition):
        main_program = self.helper.main_program
        if self.parent_idx is None:
            self.parent_idx = main_program.current_block_idx
        sub_block = main_program.create_block()
        try:
            yield
        finally:
            main_program.rollback()
        self.cases.append((condition.name, sub_block))

    @contextlib.contextmanager
    def default(self):
        main_program = self.helper.main_program
        sub_block = main_program.create_block()
        try:
            yield
        finally:
            main_program.rollback()
        self.cases.append((None, sub_block))

    @contextlib.contextmanager
    def block(self):
        try:
            yield self
        finally:
            parent_block = self.helper.main_program.block(
                self.parent_idx if self.parent_idx is not None else
                self.helper.main_program.current_block_idx)
            written = []
            for _, sb in self.cases:
                for op in sb.ops:
                    for n in op.output_arg_names:
                        if n not in sb.vars and n not in written:
                            written.append(n)
            parent_block.append_op(
                type='switch_case',
                inputs={
                    'Conditions':
                    [c for c, _ in self.cases if c is not None]
                },
                outputs={'Out': written},
                attrs={
                    'case_conds': [c for c, _ in self.cases],
                    'case_blocks': [sb for _, sb in self.cases],
                })


class IfElse(object):
    """Two-branch conditional (reference control_flow.py:1412).

    ``input(x)`` routes rows through a real ``split_lod_tensor`` op (the
    reference's data-routing substrate, operators/split_lod_tensor_op.cc):
    each branch sees its row subset compacted to the front of a
    static-shape buffer, and the ifelse op reassembles outputs with
    ``merge_lod_tensor`` semantics (per-row partition, not a blend).
    Branches that never call ``input`` fall back to computing both sides
    on the full batch and selecting per row (pure-block equivalence) —
    and a 1-element condition selects whole tensors."""

    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper('ifelse', name=name)
        self.cond = cond
        self.blocks = {}  # True/False -> sub_block
        self.outputs = {True: [], False: []}
        self.parent_idx = None
        self._out_vars = None
        self._routed = {True: False, False: False}

    @contextlib.contextmanager
    def true_block(self):
        with self._block(True):
            yield

    @contextlib.contextmanager
    def false_block(self):
        with self._block(False):
            yield

    @contextlib.contextmanager
    def _block(self, branch):
        main_program = self.helper.main_program
        if self.parent_idx is None:
            self.parent_idx = main_program.current_block_idx
        sub_block = main_program.create_block()
        self._current_branch = branch
        try:
            yield
        finally:
            main_program.rollback()
            self.blocks[branch] = sub_block

    def input(self, x):
        """Route x's rows into this branch via split_lod_tensor: the true
        branch reads OutTrue (rows where cond), the false branch OutFalse
        (reference IfElse.input, control_flow.py:1448)."""
        branch = self._current_branch
        self._routed[branch] = True
        out_true, out_false = split_lod_tensor(x, self.cond)
        return out_true if branch else out_false

    def output(self, *outs):
        self.outputs[self._current_branch].extend([o.name for o in outs])

    def __call__(self):
        if len(self.outputs[True]) != len(self.outputs[False]):
            raise ValueError('true/false branches must output equally')
        parent_block = self.helper.main_program.block(self.parent_idx)
        out_vars = []
        for t_name in self.outputs[True]:
            ov = parent_block.create_var(
                name=t_name + '@ifelse', dtype='float32')
            out_vars.append(ov)
        # declare the branches' external reads (weights, globals) as op
        # inputs so the executor threads them into the compiled state —
        # same contract as While (_external_reads above)
        ext = []
        for blk in (self.blocks.get(True), self.blocks.get(False)):
            if blk is not None:
                for n in _external_reads(blk, exclude=(self.cond.name, )):
                    if n not in ext:
                        ext.append(n)
        parent_block.append_op(
            type='ifelse',
            inputs={'Cond': [self.cond],
                    'X': ext},
            outputs={'Out': out_vars},
            attrs={
                'true_block': self.blocks.get(True),
                'false_block': self.blocks.get(False),
                'true_out': list(self.outputs[True]),
                'false_out': list(self.outputs[False]),
                'routed_true': self._routed[True],
                'routed_false': self._routed[False],
            })
        return out_vars


def split_lod_tensor(input, mask, level=0):
    """Partition input's rows by a [B, 1] bool mask into (out_true,
    out_false) — the reference's IfElse data-routing substrate
    (operators/split_lod_tensor_op.cc).  Static-shape form: each output
    keeps the full buffer with its selected rows compacted to the front
    in original order; merge_lod_tensor reconstructs exactly from the
    mask, so the padding tail is never read."""
    helper = LayerHelper('split_lod_tensor', **locals())
    out_true = helper.create_variable_for_type_inference(dtype=input.dtype)
    out_false = helper.create_variable_for_type_inference(dtype=input.dtype)
    out_true.shape = input.shape
    out_false.shape = input.shape
    helper.append_op(
        type='split_lod_tensor',
        inputs={'X': [input],
                'Mask': [mask]},
        outputs={'OutTrue': [out_true],
                 'OutFalse': [out_false]},
        attrs={'level': level})
    return out_true, out_false


def merge_lod_tensor(in_true, in_false, x, mask, level=0):
    """Inverse of split_lod_tensor (operators/merge_lod_tensor_op.cc):
    row r of the output comes from the next unconsumed compacted row of
    in_true when mask[r] else of in_false.  ``x`` carries the target
    row structure (reference uses its LoD)."""
    helper = LayerHelper('merge_lod_tensor', **locals())
    out = helper.create_variable_for_type_inference(dtype=in_true.dtype)
    out.shape = x.shape
    helper.append_op(
        type='merge_lod_tensor',
        inputs={'X': [x],
                'Mask': [mask],
                'InTrue': [in_true],
                'InFalse': [in_false]},
        outputs={'Out': [out]},
        attrs={'level': level})
    return out


def Print(input,
          first_n=-1,
          message=None,
          summarize=-1,
          print_tensor_name=True,
          print_tensor_type=True,
          print_tensor_shape=True,
          print_tensor_lod=True,
          print_phase='both'):
    """Print a tensor's value while running (reference control_flow.Print /
    operators/print_op.cc).  Lowered to the 'print' host op; returns the
    input so it can be chained in place."""
    helper = LayerHelper('print', **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    out.shape = input.shape
    helper.append_op(
        type='print',
        inputs={'In': [input]},
        outputs={'Out': [out]},
        attrs={
            'first_n': first_n,
            'message': message or '',
            'summarize': summarize,
            'print_tensor_name': print_tensor_name,
            'print_tensor_type': print_tensor_type,
            'print_tensor_shape': print_tensor_shape,
            'print_tensor_lod': print_tensor_lod,
            'print_phase': print_phase.upper(),
        })
    return out


def is_empty(x, cond=None, **ignored):
    """True iff x has zero elements (reference operators/is_empty_op.cc)."""
    helper = LayerHelper('is_empty', **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype='bool')
        cond.shape = (1, )
    helper.append_op(
        type='is_empty', inputs={'X': [x]}, outputs={'Out': [cond]})
    return cond


def lod_rank_table(x, level=0):
    """Length-descending sort permutation of x's sequences (reference
    control_flow.lod_rank_table / framework/lod_rank_table.h).  On the
    padded layout the table is the [B] index permutation sorting rows by
    length, descending, ties stable."""
    helper = LayerHelper('lod_rank_table', **locals())
    table = helper.create_variable_for_type_inference(dtype='int32')
    table.shape = (x.shape[0] if x.shape else -1, )
    helper.append_op(
        type='lod_rank_table',
        inputs={'X': [x]},
        outputs={'Out': [table]},
        attrs={'level': level})
    return table


def reorder_lod_tensor_by_rank(x, rank_table):
    """Reorder x's rows by a lod_rank_table permutation (reference
    operators/reorder_lod_tensor_by_rank_op.cc)."""
    helper = LayerHelper('reorder_lod_tensor_by_rank', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.shape = x.shape
    helper.append_op(
        type='reorder_lod_tensor_by_rank',
        inputs={'X': [x],
                'RankTable': [rank_table]},
        outputs={'Out': [out]})
    return out
