"""Input layers (reference: python/paddle/fluid/layers/io.py)."""

from .. import core
from ..framework import default_main_program, default_startup_program, \
    Variable
from ..layer_helper import LayerHelper

__all__ = ['data']


def data(name,
         shape,
         append_batch_size=True,
         dtype='float32',
         lod_level=0,
         type=core.VarDesc.VarType.LOD_TENSOR,
         stop_gradient=True):
    """Declare a feed variable (reference layers/io.py:38).

    With ``append_batch_size`` the leading dim becomes -1 (batch)."""
    helper = LayerHelper('data', name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape

    data_var = helper.create_global_variable(
        name=name,
        shape=shape,
        dtype=dtype,
        type=type,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
        is_data=True,
        persistable=False)
    return data_var
