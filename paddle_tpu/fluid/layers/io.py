"""Input layers + reader pipeline (reference: python/paddle/fluid/layers/io.py).

py_reader (reference io.py:474) feeds minibatches through the native
blocking queue (csrc/blocking_queue.cc) from a background thread; the
executor pops each batch on the host and feeds the compiled XLA step.
double_buffer() adds a device-prefetch thread that pads and stages the
next batch on device while the current step runs (the reference's
create_double_buffer_reader_op.cc behavior).
"""

import contextlib
import pickle
import threading

import numpy as np

from .. import core
from .. import unique_name
from ..framework import default_main_program, default_startup_program, \
    Variable
from ..layer_helper import LayerHelper

__all__ = ['data', 'py_reader', 'read_file', 'batch', 'double_buffer',
           'open_recordio_file', 'open_files', 'shuffle', 'Preprocessor',
           'random_data_generator']

# reader var name -> _PyReaderFeeder.  Weak values: the strong reference
# lives on the reader Variable (program lifetime), so discarding a program
# frees its feeder/queue instead of leaking per py_reader() call.
import weakref

_READER_REGISTRY = weakref.WeakValueDictionary()


def get_reader_feeder(name):
    return _READER_REGISTRY.get(name)


def data(name,
         shape,
         append_batch_size=True,
         dtype='float32',
         lod_level=0,
         type=core.VarDesc.VarType.LOD_TENSOR,
         stop_gradient=True):
    """Declare a feed variable (reference layers/io.py:38).

    With ``append_batch_size`` the leading dim becomes -1 (batch)."""
    helper = LayerHelper('data', name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape

    data_var = helper.create_global_variable(
        name=name,
        shape=shape,
        dtype=dtype,
        type=type,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
        is_data=True,
        persistable=False)
    return data_var


class _PyReaderFeeder(object):
    """Producer side of a py_reader: background thread -> native queue."""

    def __init__(self, capacity, shapes, dtypes, lod_levels):
        from ...runtime import NativeBlockingQueue
        self.queue = NativeBlockingQueue(capacity)
        self.capacity = capacity
        self._closed = False
        self.shapes = shapes
        self.dtypes = dtypes
        self.lod_levels = lod_levels or [0] * len(shapes)
        self._provider = None
        self._thread = None
        self._exhausted = False
        self._error = None
        self._shuffle_buffer = 0
        # one batch handed back by a consumer that drained past a
        # shape-bucket boundary (reader-fed run_multi): delivered again
        # by the next pop of the SAME pass
        self._pushback = None
        # serializes pass-boundary state (generation, _exhausted,
        # _error) against a pop() racing reset()+start()
        self._gen_lock = threading.RLock()
        # set by double_buffer(): batches are padded + device_put on a
        # prefetch thread so transfer of batch N+1 overlaps step N
        self._double_buffer_place = None
        self._double_buffer_requested = False
        self._executor_place = None  # bound by the consuming Executor
        self._dev_queue = None
        self._convert_thread = None

    def _effective_db_place(self):
        """Prefetch target: explicit double_buffer place, else the place
        of the executor consuming THIS reader (bound per-feeder at pop
        time), else the place of whichever executor last ran (covers the
        batches converted before the first pop), else the build
        default."""
        if self._double_buffer_place is not None:
            return self._double_buffer_place
        if self._executor_place is not None:
            return self._executor_place
        if _last_executor_place is not None:
            return _last_executor_place
        return core.TPUPlace() if core.is_compiled_with_tpu() \
            else core.CPUPlace()

    def decorate_paddle_reader(self, reader, places=None):
        """reader yields per-sample tuples; batches are assembled with
        DataFeeder semantics by the caller via paddle.batch-style readers
        that already yield lists of samples."""
        from ..data_feeder import DataToLoDTensorConverter

        def provider():
            for batch_rows in reader():
                converters = [
                    DataToLoDTensorConverter(None, lod, shape, dtype)
                    for lod, shape, dtype in zip(
                        self.lod_levels, self.shapes, self.dtypes)
                ]
                for row in batch_rows:
                    for conv, slot in zip(converters, row):
                        conv.feed(slot)
                yield tuple(c.done() for c in converters)

        self._provider = provider

    def decorate_tensor_provider(self, provider):
        """provider yields tuples of numpy arrays / LoDTensors directly."""

        def gen():
            for item in provider():
                yield tuple(item)

        self._provider = gen

    def start(self):
        if self._provider is None:
            raise RuntimeError('decorate a data source before start()')
        with self._gen_lock:
            self.queue.reopen()
            self._exhausted = False
            self._error = None
            # every pass is one generation: pop()/push_back() compare
            # against it so an aborted pass can neither hang on a dead
            # queue nor leak state into a restarted one
            self._generation = getattr(self, '_generation', 0) + 1

        provider = self._provider
        if self._shuffle_buffer > 1:
            provider = _shuffled_provider(provider, self._shuffle_buffer)

        if self._double_buffer_requested:
            self._start_zero_copy_pipeline(provider)
            return

        def work():
            try:
                for batch in provider():
                    # in-process framing only (never persisted to disk)
                    if not self.queue.push(pickle.dumps(batch, protocol=4)):
                        return
            except BaseException as e:  # surface to the consumer, not EOF
                self._error = e
            finally:
                self.queue.close()

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    # ---- double-buffer device prefetch (reference
    # operators/reader/create_double_buffer_reader_op.cc: a prefetch
    # thread keeps the next batch resident on device).  Batches move
    # producer -> converter as PYTHON REFERENCES, not serialized bytes:
    # at ResNet batch sizes the pickle+queue+unpickle round trip costs
    # more than the training step itself. ----
    def _convert_batch(self, item):
        import jax
        from ..executor import _lod_to_padded
        dev = self._effective_db_place().jax_device()
        out = []
        for slot in item:
            if isinstance(slot, core.LoDTensor) and slot.lod():
                padded, lengths = _lod_to_padded(slot)
                lod = slot.lod()
                rows = None
                if len(lod) >= 2:  # nested: keep the outer level too
                    outer = np.asarray(lod[0], np.int64)
                    rows = jax.device_put(
                        (outer[1:] - outer[:-1]).astype(np.int32), dev)
                out.append(
                    core.PaddedSequence(
                        jax.device_put(padded, dev),
                        jax.device_put(lengths, dev), rows))
            else:
                arr = slot.numpy() if isinstance(slot, core.LoDTensor) \
                    else np.asarray(slot)
                out.append(jax.device_put(arr, dev))
        return tuple(out)

    def _start_zero_copy_pipeline(self, provider):
        import queue as _queue
        end = object()
        # locals captured by the closures: a thread from a PREVIOUS
        # generation that outlives reset() keeps touching ITS queues and
        # can never corrupt the next epoch's state
        ref_q = _queue.Queue(maxsize=max(2, min(int(self.capacity), 8)))
        dev_q = _queue.Queue(maxsize=2)
        with self._gen_lock:
            # the pass state flips atomically w.r.t. a pop() snapshot:
            # a consumer never sees the new generation with the OLD (or
            # a missing) device queue and route/poll the wrong stream
            self._closed = False
            gen = self._generation  # bumped by start(), the only caller
            self._dev_queue = dev_q

        def _live():
            return not self._closed and self._generation == gen

        def _put(q, item):
            while _live():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except _queue.Full:
                    continue
            return False

        def _record_error(e):
            if _live():
                self._error = e

        def produce():
            try:
                for batch in provider():
                    if not _put(ref_q, tuple(batch)):
                        return
            except BaseException as e:
                _record_error(e)
            finally:
                _put(ref_q, end)

        def convert():
            try:
                while _live():
                    try:
                        item = ref_q.get(timeout=0.1)
                    except _queue.Empty:
                        continue
                    if item is end:
                        _put(dev_q, None)
                        return
                    _put(dev_q, self._convert_batch(item))
            except BaseException as e:
                _record_error(e)
                _put(dev_q, None)

        with self._gen_lock:
            self._thread = threading.Thread(target=produce, daemon=True)
            self._convert_thread = threading.Thread(target=convert,
                                                    daemon=True)
        self._thread.start()
        self._convert_thread.start()

    def _eof_or_raise(self):
        """End of stream: surface a provider error once, then signal EOF
        on this and every later pop until reset()."""
        self._exhausted = True
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(
                'py_reader data provider failed: %r' % (err, )) from err
        return None

    def push_back(self, batch):
        """Hand ONE popped batch back to the stream: the next pop of
        this pass delivers it again (reader-fed run_multi drains up to
        a shape-bucket boundary and returns the first differing batch
        here instead of dropping it).  Generation-stamped: a batch
        whose pass was reset() between the pop and the push-back is
        DROPPED, never leaked into a restarted pass's stream."""
        with self._gen_lock:
            if getattr(self, '_generation', 0) == \
                    getattr(self, '_last_pop_gen', 0):
                self._pushback = batch

    def pop(self):
        with self._gen_lock:
            # one consistent pass snapshot: reset()/start() mutate the
            # pushback, queue, flags and generation under this lock, so
            # the held batch we deliver, the queue we poll below and
            # the generation we compare against can never straddle a
            # pass boundary.  Routing keys on the device queue ALONE
            # (its presence is the zero-copy pass marker) — no second
            # field to read consistently.
            if self._pushback is not None:
                batch, self._pushback = self._pushback, None
                return batch
            dev_q = self._dev_queue
            gen = self._last_pop_gen = getattr(self, '_generation', 0)
        if dev_q is not None:
            if self._exhausted:  # the sentinel is delivered only once
                return None
            import queue as _queue_mod
            while True:
                try:
                    batch = dev_q.get(timeout=0.1)
                    break
                except _queue_mod.Empty:
                    if self._closed or self._generation != gen:
                        # reset() raced this pop: the generation's
                        # workers exit WITHOUT delivering the sentinel,
                        # so a bare get() would hang forever.  Under
                        # the gen lock, signal EOF (or the provider's
                        # error) for THIS pass — if reset()+start()
                        # already began the next generation, report
                        # plain EOF without poisoning its state.
                        with self._gen_lock:
                            if getattr(self, '_generation', 0) != gen:
                                return None
                            return self._eof_or_raise()
            if batch is None:
                return self._eof_or_raise()
            return batch
        data = self.queue.pop()
        if data is None:
            return self._eof_or_raise()
        return pickle.loads(data)

    def reset(self):
        with self._gen_lock:
            self._pushback = None  # a held batch dies with its pass
            self.queue.close()
            self._closed = True
        if self._convert_thread is not None:
            self._convert_thread.join(timeout=5)
            self._convert_thread = None
            self._dev_queue = None
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._thread = None


def py_reader(capacity,
              shapes,
              dtypes,
              lod_levels=None,
              name=None,
              use_double_buffer=True):
    """Create a feedable reader (reference layers/io.py:474).

    Returns a reader Variable with ``decorate_paddle_reader`` /
    ``decorate_tensor_provider`` / ``start`` / ``reset`` methods; pair with
    :func:`read_file` to get the data variables."""
    helper = LayerHelper('py_reader', name=name)
    reader = helper.create_global_variable(
        name=unique_name.generate('create_py_reader'),
        type=core.VarDesc.VarType.READER,
        persistable=True)
    feeder = _PyReaderFeeder(capacity, list(shapes), list(dtypes),
                             lod_levels)
    reader._feeder = feeder  # strong ref: feeder lives as long as the var
    _READER_REGISTRY[reader.name] = feeder
    reader._shapes = list(shapes)
    reader._dtypes = list(dtypes)
    reader._lod_levels = lod_levels or [0] * len(shapes)
    reader.decorate_paddle_reader = feeder.decorate_paddle_reader
    reader.decorate_tensor_provider = feeder.decorate_tensor_provider
    reader.start = feeder.start
    reader.reset = feeder.reset
    return reader


def read_file(reader):
    """Emit the read op producing this reader's data vars
    (reference layers/io.py read_file)."""
    helper = LayerHelper('read_file')
    out = []
    for shape, dtype, lod in zip(reader._shapes, reader._dtypes,
                                 reader._lod_levels):
        v = helper.create_variable_for_type_inference(
            dtype, stop_gradient=True)
        v.shape = tuple(shape)
        v.lod_level = lod
        v.is_data = True
        out.append(v)
    helper.append_op(
        type='read',
        inputs={'Reader': [reader]},
        outputs={'Out': out})
    if len(out) == 1:
        return out[0]
    return out


def batch(reader, batch_size):
    """Kept for reader-pipeline API parity; batching happens host-side."""
    return reader


def note_executor_place(place):
    """Called by Executor.run: remembers the live execution place so
    double_buffer(place=None) prefetches to the device actually running
    the program (a CPU-place Executor on a TPU build must NOT get its
    batches staged to the TPU)."""
    global _last_executor_place
    _last_executor_place = place


_last_executor_place = None


def double_buffer(reader, place=None, name=None):
    """Stage batches on device one step ahead (reference layers/io.py:891,
    create_double_buffer_reader_op.cc): a prefetch thread pads LoD slots
    and ``device_put``s every slot, so the host->device transfer of batch
    N+1 overlaps device execution of step N.  Takes effect at the
    reader's next ``start()``.  With ``place=None`` the target device is
    resolved lazily per batch from the executor that last ran (falling
    back to the build default before any run); a mis-staged early batch
    is re-put by the executor's feed conversion, so this is a perf
    default, never a correctness choice."""
    feeder = get_reader_feeder(reader.name)
    if feeder is not None:
        feeder._double_buffer_place = place
        feeder._double_buffer_requested = True
    return reader


def _shuffled_provider(provider, buffer_size):
    import random

    def gen():
        buf = []
        for item in provider():
            buf.append(item)
            if len(buf) >= buffer_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        random.shuffle(buf)
        for b in buf:
            yield b

    return gen


def shuffle(reader, buffer_size):
    """Shuffle a py_reader's batches through a host-side reservoir
    (reference layers/io.py shuffle created a shuffle-reader op)."""
    feeder = get_reader_feeder(reader.name)
    if feeder is not None:
        feeder._shuffle_buffer = int(buffer_size)
    return reader


def _decode_npz_record(rec):
    """recordio records are npz-framed numpy tuples (data-only, no code
    execution) — shared by every recordio reader layer."""
    import io as _io
    with np.load(_io.BytesIO(rec), allow_pickle=False) as z:
        return tuple(z['arr_%d' % i] for i in range(len(z.files)))


def _scan_file(filename):
    from ...runtime import RecordIOScanner
    scanner = RecordIOScanner(filename)
    try:
        for rec in scanner:
            yield _decode_npz_record(rec)
    finally:
        scanner.close()


def open_recordio_file(filename,
                       shapes,
                       dtypes,
                       lod_levels=None,
                       pass_num=1,
                       for_parallel=True):
    """Reader over a recordio file written by
    paddle_tpu.recordio / fluid.recordio_writer (reference
    operators/reader/create_recordio_file_reader_op.cc)."""
    rd = py_reader(64, shapes, dtypes, lod_levels)

    def provider():
        for _ in range(pass_num):
            for item in _scan_file(filename):
                yield item

    rd.decorate_tensor_provider(provider)
    return rd


def open_files(filenames,
               shapes,
               lod_levels,
               dtypes,
               thread_num=None,
               buffer_size=None,
               pass_num=1,
               is_test=None):
    """Multi-file multi-thread recordio reader (reference layers/io.py:724;
    operators/reader/open_files_op.cc).  is_test (or thread_num == 1)
    preserves file order; otherwise reader threads interleave files."""
    import queue as _queue

    thread_num = (1 if is_test else
                  min(thread_num or len(filenames), len(filenames)))
    buffer_size = buffer_size or 3 * thread_num
    rd = py_reader(buffer_size, shapes, dtypes, lod_levels)

    def provider():
        for _ in range(pass_num):
            if thread_num == 1:
                for fname in filenames:
                    for item in _scan_file(fname):
                        yield item
                continue
            q = _queue.Queue(maxsize=buffer_size)
            done = object()
            errors = []

            def work(my_files):
                try:
                    for fname in my_files:
                        for item in _scan_file(fname):
                            q.put(item)
                except BaseException as e:
                    # surface reader failures to the consumer: silently
                    # truncating the dataset would look like a clean EOF
                    errors.append(e)
                finally:
                    q.put(done)

            shards = [filenames[i::thread_num] for i in range(thread_num)]
            workers = [
                threading.Thread(target=work, args=(shard, ), daemon=True)
                for shard in shards
            ]
            for w in workers:
                w.start()
            finished = 0
            while finished < thread_num:
                item = q.get()
                if item is done:
                    finished += 1
                else:
                    yield item
            for w in workers:
                w.join()
            if errors:
                raise RuntimeError(
                    'open_files reader thread failed: %r' %
                    (errors[0], )) from errors[0]

    rd.decorate_tensor_provider(provider)
    return rd


def random_data_generator(low, high, shapes, lod_levels, for_parallel=True):
    """Uniform-random dummy reader (reference layers/io.py:410,
    operators/reader/create_random_data_generator_op.cc): a reader
    Variable that synthesizes float32 batches itself — no file, no
    start() needed.  Pair with read_file to get the data vars."""
    shapes = [list(s) for s in shapes]
    reader = py_reader(
        capacity=4,
        shapes=shapes,
        dtypes=['float32'] * len(shapes),
        lod_levels=list(lod_levels))
    rng = np.random.RandomState(0)

    def provider():
        while True:
            yield tuple(
                rng.uniform(low, high, size=s).astype('float32')
                for s in shapes)

    feeder = get_reader_feeder(reader.name)
    feeder.decorate_tensor_provider(provider)
    feeder.start()
    return reader


class Preprocessor(object):
    """Custom reader-transform block (reference layers/io.py Preprocessor /
    operators/reader/create_custom_reader_op.cc): a sub-block of ops is
    defined between ``inputs()`` and ``outputs()`` and applied to every
    batch the underlying reader yields.

    TPU-native mechanism: the block's ops run through the same XLA
    lowering registry as any program — per batch, on the host-visible
    feed path — by executing a tiny derived Program over the popped
    batch, then pushing the transformed slots onward.  The returned
    reader var swaps its feeder for the transforming one at ``start``.
    """

    BEFORE_SUB_BLOCK = 0
    IN_SUB_BLOCK = 1
    AFTER_SUB_BLOCK = 2

    def __init__(self, reader, name=None):
        self.underlying = reader
        self.helper = LayerHelper('create_custom_reader', name=name)
        self.status = Preprocessor.BEFORE_SUB_BLOCK
        self.main_prog = self.helper.main_program
        self.sub_block = None
        self.source_vars = None
        self.sink_vars = None

    def _is_completed(self):
        return self.sub_block and self.source_vars and self.sink_vars

    @contextlib.contextmanager
    def block(self):
        self.status = Preprocessor.IN_SUB_BLOCK
        self.sub_block = self.main_prog.create_block()
        try:
            yield
        finally:
            self.main_prog.rollback()
            self.status = Preprocessor.AFTER_SUB_BLOCK
            if not self._is_completed():
                raise RuntimeError(
                    'Preprocessor block needs inputs() and outputs()')
            self._install()

    def inputs(self):
        if self.status != Preprocessor.IN_SUB_BLOCK:
            raise RuntimeError(
                'Preprocessor.inputs() must be called inside block()')
        feeder = get_reader_feeder(self.underlying.name)
        self.source_vars = []
        for i, (shape, dtype) in enumerate(
                zip(feeder.shapes, feeder.dtypes)):
            v = self.sub_block.create_var(
                name=unique_name.generate('preprocessor_src_%d' % i),
                dtype=dtype)
            v.shape = tuple(shape)
            self.source_vars.append(v)
        return self.source_vars

    def outputs(self, *outs):
        if self.status != Preprocessor.IN_SUB_BLOCK:
            raise RuntimeError(
                'Preprocessor.outputs() must be called inside block()')
        self.sink_vars = list(outs)

    def _install(self):
        from ..executor import Executor
        src_names = [v.name for v in self.source_vars]
        sink_names = [v.name for v in self.sink_vars]
        # derived per-batch program: the sub-block's ops over feed vars
        from ..framework import Program
        prog = Program()
        blk = prog.global_block()
        for v in self.source_vars:
            nv = blk.create_var(name=v.name, dtype=v.dtype)
            nv.shape = getattr(v, 'shape', None)
            nv.is_data = True
        for op in self.sub_block.ops:
            blk.append_op(type=op.type, inputs=dict(op.inputs),
                          outputs=dict(op.outputs), attrs=dict(op.attrs))
        for name, v in self.sub_block.vars.items():
            if name not in blk.vars:
                blk.vars[name] = v
        underlying_feeder = get_reader_feeder(self.underlying.name)
        exe = Executor(core.CPUPlace())

        original_pop = underlying_feeder.pop

        def transforming_pop():
            batch = original_pop()
            if batch is None:
                return None
            feed = dict(zip(src_names, batch))
            outs = exe.run(prog, feed=feed, fetch_list=sink_names)
            return tuple(np.asarray(o) for o in outs)

        underlying_feeder.pop = transforming_pop

    def __call__(self):
        return self.underlying
