"""Sequence layers (reference: python/paddle/fluid/layers/nn.py — the
sequence_* / dynamic_lstm / dynamic_gru family).

LoD inputs lower to padded [B, T, ...] + carried lengths (SURVEY §5.7);
every layer here emits the masked-dense ops from
paddle_tpu.ops.sequence_ops.
"""

from ..layer_helper import LayerHelper
from ..framework import Variable
from ..initializer import Constant

__all__ = [
    'dynamic_lstm', 'dynamic_gru', 'gru_unit', 'sequence_conv',
    'sequence_pool', 'sequence_softmax', 'sequence_first_step',
    'sequence_last_step', 'sequence_expand', 'sequence_concat',
    'sequence_reshape', 'sequence_enumerate', 'sequence_erase',
    'sequence_reverse',
    'dynamic_lstmp',
    'sequence_slice', 'row_conv', 'sequence_pad', 'sequence_mask',
    'beam_search', 'beam_search_decode', 'beam_expand', 'beam_init_scores',
]


def dynamic_lstm(input,
                 size,
                 h_0=None,
                 c_0=None,
                 param_attr=None,
                 bias_attr=None,
                 use_peepholes=True,
                 is_reverse=False,
                 gate_activation='sigmoid',
                 cell_activation='tanh',
                 candidate_activation='tanh',
                 dtype='float32',
                 name=None):
    """LSTM over a whole (variable-length) batch: input is the
    pre-projected gate sequence [*, 4D] (reference nn.py dynamic_lstm,
    operators/lstm_op.cc); lowered to lax.scan."""
    helper = LayerHelper('lstm', **locals())
    hidden_dim = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden_dim, 4 * hidden_dim],
        dtype=dtype)
    bias_size = [1, 7 * hidden_dim if use_peepholes else 4 * hidden_dim]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True)

    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    batch_gate = helper.create_variable_for_type_inference(dtype)
    batch_cell_pre_act = helper.create_variable_for_type_inference(dtype)
    hidden.shape = tuple(input.shape[:-1]) + (hidden_dim, )
    cell.shape = hidden.shape
    hidden.lod_level = input.lod_level
    cell.lod_level = input.lod_level
    inputs = {'Input': [input], 'Weight': [weight], 'Bias': [bias]}
    if h_0 is not None:
        inputs['H0'] = [h_0]
    if c_0 is not None:
        inputs['C0'] = [c_0]
    helper.append_op(
        type='lstm',
        inputs=inputs,
        outputs={
            'Hidden': [hidden],
            'Cell': [cell],
            'BatchGate': [batch_gate],
            'BatchCellPreAct': [batch_cell_pre_act]
        },
        attrs={
            'use_peepholes': use_peepholes,
            'is_reverse': is_reverse,
            'gate_activation': gate_activation,
            'cell_activation': cell_activation,
            'candidate_activation': candidate_activation
        })
    return hidden, cell


def dynamic_gru(input,
                size,
                param_attr=None,
                bias_attr=None,
                is_reverse=False,
                gate_activation='sigmoid',
                candidate_activation='tanh',
                h_0=None):
    """GRU over a batch: input pre-projected [*, 3D]
    (reference nn.py dynamic_gru, operators/gru_op.cc)."""
    helper = LayerHelper('gru', **locals())
    dtype = helper.input_dtype()
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype,
        is_bias=True)
    hidden = helper.create_variable_for_type_inference(dtype)
    hidden.shape = tuple(input.shape[:-1]) + (size, )
    hidden.lod_level = input.lod_level
    batch_gate = helper.create_variable_for_type_inference(dtype)
    batch_reset = helper.create_variable_for_type_inference(dtype)
    batch_hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {'Input': [input], 'Weight': [weight], 'Bias': [bias]}
    if h_0 is not None:
        inputs['H0'] = [h_0]
    helper.append_op(
        type='gru',
        inputs=inputs,
        outputs={
            'Hidden': [hidden],
            'BatchGate': [batch_gate],
            'BatchResetHiddenPrev': [batch_reset],
            'BatchHidden': [batch_hidden]
        },
        attrs={
            'is_reverse': is_reverse,
            'gate_activation': gate_activation,
            'activation': candidate_activation
        })
    return hidden


def gru_unit(input,
             hidden,
             size,
             param_attr=None,
             bias_attr=None,
             activation='tanh',
             gate_activation='sigmoid'):
    """Single GRU step (reference nn.py gru_unit)."""
    activation_dict = dict(identity=0, sigmoid=1, tanh=2, relu=3)
    helper = LayerHelper('gru_unit', **locals())
    dtype = helper.input_dtype()
    size = size // 3
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden_pre = helper.create_variable_for_type_inference(dtype)
    updated_hidden = helper.create_variable_for_type_inference(dtype)
    updated_hidden.shape = hidden.shape
    inputs = {'Input': [input], 'HiddenPrev': [hidden], 'Weight': [weight]}
    if helper.bias_attr:
        bias = helper.create_parameter(
            attr=helper.bias_attr, shape=[1, 3 * size], dtype=dtype,
            is_bias=True)
        inputs['Bias'] = [bias]
    helper.append_op(
        type='gru_unit',
        inputs=inputs,
        outputs={
            'Gate': [gate],
            'ResetHiddenPrev': [reset_hidden_pre],
            'Hidden': [updated_hidden],
        },
        attrs={
            'activation': activation_dict[activation],
            'gate_activation': activation_dict[gate_activation],
        })
    return updated_hidden, reset_hidden_pre, gate


def sequence_conv(input,
                  num_filters,
                  filter_size=3,
                  filter_stride=1,
                  padding=None,
                  bias_attr=None,
                  param_attr=None,
                  act=None):
    """Context-window conv over time (reference nn.py sequence_conv)."""
    helper = LayerHelper('sequence_conv', **locals())
    dtype = helper.input_dtype()
    filter_shape = [filter_size * input.shape[-1], num_filters]
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    pre_bias.shape = tuple(input.shape[:-1]) + (num_filters, )
    pre_bias.lod_level = input.lod_level
    helper.append_op(
        type='sequence_conv',
        inputs={
            'X': [input],
            'Filter': [filter_param],
        },
        outputs={'Out': [pre_bias]},
        attrs={
            'contextStride': filter_stride,
            'contextStart': -int(filter_size // 2),
            'contextLength': filter_size
        })
    pre_act = helper.append_bias_op(pre_bias,
                                    dim_start=len(pre_bias.shape) - 1)
    return helper.append_activation(pre_act)


def sequence_pool(input, pool_type, agg_to_no_sequence=False):
    """Pool each sequence to one vector (reference nn.py sequence_pool;
    pool_type: sum/average/sqrt/max/last/first).  On a NESTED (2-level
    LoD) input the FLUID default matches reference fluid: pool the LAST
    LoD level (each sub-sequence), yielding a plain sequence.
    ``agg_to_no_sequence=True`` is the legacy v2 AggregateLevel
    .TO_NO_SEQUENCE (pool the whole nested sample) — v2/tch
    pooling_layer pass it explicitly."""
    helper = LayerHelper('sequence_pool', **locals())
    dtype = helper.input_dtype()
    pool_out = helper.create_variable_for_type_inference(dtype)
    max_index = helper.create_variable_for_type_inference(dtype='int32')
    if len(input.shape) >= 2:
        pool_out.shape = (input.shape[0], input.shape[-1])
    helper.append_op(
        type='sequence_pool',
        inputs={'X': [input]},
        outputs={'Out': [pool_out],
                 'MaxIndex': [max_index]},
        attrs={'pooltype': pool_type.upper(),
               'agg_to_no_sequence': bool(agg_to_no_sequence)})
    if pool_type == 'max':
        max_index.stop_gradient = True
    return pool_out


def sequence_first_step(input):
    return sequence_pool(input=input, pool_type='first')


def sequence_last_step(input):
    return sequence_pool(input=input, pool_type='last')


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper('sequence_softmax', **locals())
    dtype = helper.input_dtype()
    softmax_out = helper.create_variable_for_type_inference(dtype)
    softmax_out.shape = input.shape
    softmax_out.lod_level = input.lod_level
    helper.append_op(
        type='sequence_softmax',
        inputs={'X': [input]},
        outputs={'Out': [softmax_out]})
    return softmax_out


def sequence_expand(x, y, ref_level=-1, name=None,
                    expand_from_sequence=False):
    """``expand_from_sequence`` selects the legacy
    ExpandLevel.FROM_SEQUENCE on a nested ref: each item of the plain
    sequence x broadcasts across the matching sub-sequence of y."""
    helper = LayerHelper('sequence_expand', **locals())
    dtype = helper.input_dtype('x')
    tmp = helper.create_variable_for_type_inference(dtype)
    tmp.lod_level = y.lod_level
    helper.append_op(
        type='sequence_expand',
        inputs={'X': [x],
                'Y': [y]},
        outputs={'Out': [tmp]},
        attrs={'ref_level': ref_level,
               'expand_from_sequence': bool(expand_from_sequence)})
    return tmp


def sequence_reverse(x, name=None):
    """Reverse each sequence along time, mask-aware (padding stays in
    place).  The input transform behind reverse recurrences
    (reference operators/reverse_op.cc; RecurrentGradientMachine's
    reversed scan)."""
    helper = LayerHelper('sequence_reverse', **locals())
    out = helper.create_variable_for_type_inference(
        dtype=helper.input_dtype('x'))
    out.shape = x.shape
    out.lod_level = x.lod_level
    helper.append_op(
        type='sequence_reverse',
        inputs={'X': [x]},
        outputs={'Out': [out]})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper('sequence_concat', **locals())
    out = helper.create_variable_for_type_inference(
        dtype=helper.input_dtype())
    helper.append_op(
        type='sequence_concat',
        inputs={'X': input},
        outputs={'Out': [out]})
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper('sequence_reshape', **locals())
    out = helper.create_variable_for_type_inference(
        dtype=helper.input_dtype())
    helper.append_op(
        type='sequence_reshape',
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={'new_dim': new_dim})
    return out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper('sequence_enumerate', **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype(), stop_gradient=True)
    helper.append_op(
        type='sequence_enumerate',
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={'win_size': win_size,
               'pad_value': pad_value})
    return out


def sequence_erase(input, tokens, name=None):
    helper = LayerHelper('sequence_erase', **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(
        type='sequence_erase',
        inputs={'X': [input]},
        outputs={'Out': [out]},
        attrs={'tokens': list(tokens)})
    return out


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper('sequence_slice', **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(
        type='sequence_slice',
        inputs={'X': [input],
                'Offset': [offset],
                'Length': [length]},
        outputs={'Out': [out]})
    return out


def sequence_pad(x, pad_value, maxlen=None):
    helper = LayerHelper('sequence_pad', **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype('x'))
    length = helper.create_variable_for_type_inference('int64')
    helper.append_op(
        type='sequence_pad',
        inputs={'X': [x],
                'PadValue': [pad_value]},
        outputs={'Out': [out],
                 'Length': [length]},
        attrs={'padded_length': maxlen if maxlen is not None else -1})
    return out, length


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead convolution (reference nn.py row_conv)."""
    helper = LayerHelper('row_conv', **locals())
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[-1]]
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = input.shape
    out.lod_level = input.lod_level
    helper.append_op(
        type='row_conv',
        inputs={'X': [input],
                'Filter': [filter_param]},
        outputs={'Out': [out]})
    return helper.append_activation(out)


def sequence_mask(x, maxlen=None, dtype='int64', name=None):
    """lengths tensor [B] -> 0/1 mask [B, maxlen] (reference
    layers sequence_mask / math/sequence_padding.h)."""
    helper = LayerHelper('sequence_mask', **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type='sequence_mask',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'maxlen': maxlen if maxlen is not None else -1,
               'out_dtype': dtype})
    return out


def beam_expand(x, beam_size):
    """Tile per-sentence rows to per-beam rows [B,...] -> [B*K,...]
    (dense analog of the reference decoder's LoD beam expansion)."""
    helper = LayerHelper('beam_expand', **locals())
    out = helper.create_variable_for_type_inference(
        helper.input_dtype('x'))
    out.shape = tuple(x.shape)
    out.lod_level = x.lod_level
    helper.append_op(
        type='beam_expand',
        inputs={'X': [x]},
        outputs={'Out': [out]},
        attrs={'beam_size': beam_size})
    return out


def beam_init_scores(ref, beam_size):
    """Initial accumulated scores [B*K, 1]: 0 for beam 0, -1e9 others."""
    helper = LayerHelper('beam_init_scores', **locals())
    out = helper.create_variable_for_type_inference('float32')
    out.shape = (-1, 1)
    helper.append_op(
        type='beam_init_scores',
        inputs={'X': [ref]},
        outputs={'Out': [out]},
        attrs={'beam_size': beam_size})
    return out


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, row_offsets=None, name=None):
    """One beam-search step (reference layers beam_search,
    operators/beam_search_op.cc) on the static [B*K] beam layout.
    Returns (selected_ids, selected_scores, parent_idx).

    ``level`` selects the grouping LoD level exactly like the reference
    (``ToAbsOffset(ids.lod())[level]`` delimits the selection pools):
    level 0 pools rows per source sentence (uniform K blocks, or the
    explicit ``row_offsets`` for ragged sentence->candidate nesting —
    the static carrier of the reference's 2-level LoD), level 1 makes
    every candidate row its own pool (the beam-growth step).  Pool
    selection, finished-row carry, and per-parent output grouping
    follow beam_search_op.cc; tests/test_beam_search.py pins the
    contract against a numpy oracle of that kernel."""
    helper = LayerHelper('beam_search', **locals())
    selected_ids = helper.create_variable_for_type_inference('int64')
    selected_scores = helper.create_variable_for_type_inference('float32')
    parent_idx = helper.create_variable_for_type_inference('int32')
    attrs = {'beam_size': beam_size, 'end_id': end_id, 'level': level}
    if row_offsets is not None:
        attrs['row_offsets'] = [int(o) for o in row_offsets]
    helper.append_op(
        type='beam_search',
        inputs={
            'pre_ids': [pre_ids],
            'pre_scores': [pre_scores],
            'ids': [ids],
            'scores': [scores],
        },
        outputs={
            'selected_ids': [selected_ids],
            'selected_scores': [selected_scores],
            'parent_idx': [parent_idx],
        },
        attrs=attrs)
    return selected_ids, selected_scores, parent_idx


def beam_search_decode(ids, scores, parent_idx, beam_size, end_id,
                       name=None):
    """Backtrack stacked per-step beams into sentences (reference layers
    beam_search_decode, operators/beam_search_decode_op.cc).
    Returns (sentence_ids [B,K,T], sentence_scores [B,K])."""
    helper = LayerHelper('beam_search_decode', **locals())
    sentence_ids = helper.create_variable_for_type_inference('int64')
    sentence_scores = helper.create_variable_for_type_inference('float32')
    helper.append_op(
        type='beam_search_decode',
        inputs={'Ids': [ids],
                'Scores': [scores],
                'ParentIdx': [parent_idx]},
        outputs={'SentenceIds': [sentence_ids],
                 'SentenceScores': [sentence_scores]},
        attrs={'beam_size': beam_size,
               'end_id': end_id})
    return sentence_ids, sentence_scores


def dynamic_lstmp(input,
                  size,
                  proj_size,
                  param_attr=None,
                  bias_attr=None,
                  use_peepholes=True,
                  is_reverse=False,
                  gate_activation='sigmoid',
                  cell_activation='tanh',
                  candidate_activation='tanh',
                  proj_activation='tanh',
                  dtype='float32',
                  name=None):
    """Projected LSTM (reference nn.py dynamic_lstmp;
    operators/lstmp_op.cc).  Returns (projection, cell)."""
    helper = LayerHelper('lstmp', **locals())
    hidden_dim = size // 4
    weight = helper.create_parameter(
        attr=helper.param_attr, shape=[proj_size, 4 * hidden_dim],
        dtype=dtype)
    proj_weight = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden_dim, proj_size], dtype=dtype)
    bias_size = [1, 7 * hidden_dim if use_peepholes else 4 * hidden_dim]
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=bias_size, dtype=dtype, is_bias=True)

    projection = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    batch_gate = helper.create_variable_for_type_inference(dtype)
    batch_hidden = helper.create_variable_for_type_inference(dtype)
    projection.shape = tuple(input.shape[:-1]) + (proj_size, )
    cell.shape = tuple(input.shape[:-1]) + (hidden_dim, )
    projection.lod_level = input.lod_level
    cell.lod_level = input.lod_level
    helper.append_op(
        type='lstmp',
        inputs={'Input': [input],
                'Weight': [weight],
                'ProjWeight': [proj_weight],
                'Bias': [bias]},
        outputs={
            'Projection': [projection],
            'Cell': [cell],
            'BatchGate': [batch_gate],
            'BatchHidden': [batch_hidden]
        },
        attrs={
            'use_peepholes': use_peepholes,
            'is_reverse': is_reverse,
            'gate_activation': gate_activation,
            'cell_activation': cell_activation,
            'candidate_activation': candidate_activation,
            'proj_activation': proj_activation
        })
    return projection, cell
