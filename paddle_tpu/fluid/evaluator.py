"""Graph-state evaluators (reference: python/paddle/fluid/evaluator.py).

An Evaluator owns persistable state vars updated by graph ops each
minibatch plus an ``eval`` program reading them — the reference pattern.
"""

import numpy as np

from . import layers
from .framework import Program, Variable, program_guard
from .layer_helper import LayerHelper
from .initializer import Constant
from .executor import global_scope

__all__ = ['Accuracy', 'ChunkEvaluator', 'Evaluator']


class Evaluator(object):
    def __init__(self, name, **kwargs):
        self.helper = LayerHelper(name, **kwargs)
        self.states = []
        self.metrics = []

    def reset(self, executor, reset_program=None):
        scope = global_scope()
        for var in self.states:
            v = scope.find_var(var.name)
            if v is not None and v.value() is not None:
                import numpy as _np
                old = v.value()
                arr = old.numpy() if hasattr(old, 'numpy') else \
                    _np.asarray(old)
                v.set_value(_np.zeros_like(arr))

    def eval(self, executor, eval_program=None):
        raise NotImplementedError()

    def _create_state(self, suffix, dtype, shape):
        state = self.helper.create_global_variable(
            name='_'.join([unique_name(self.helper.name), suffix]),
            persistable=True,
            dtype=dtype,
            shape=shape)
        self.helper.set_variable_initializer(state, Constant(0.0))
        self.states.append(state)
        return state


def unique_name(prefix):
    from . import unique_name as un
    return un.generate(prefix)


class Accuracy(Evaluator):
    """Streaming accuracy (reference evaluator.py Accuracy)."""

    def __init__(self, input, label, k=1, **kwargs):
        super(Accuracy, self).__init__('accuracy', **kwargs)
        main_program = self.helper.main_program
        if main_program.current_block().idx != 0:
            raise ValueError('You can only invoke Evaluator in root block')

        self.total = self._create_state(dtype='int64', shape=[1],
                                        suffix='total')
        self.correct = self._create_state(dtype='int64', shape=[1],
                                          suffix='correct')
        total = self.helper.create_variable_for_type_inference(dtype='int64')
        correct = self.helper.create_variable_for_type_inference(
            dtype='int64')
        acc = layers.accuracy(
            input=input, label=label, k=k, correct=correct, total=total)
        layers.sums(input=[self.total, total], out=self.total)
        layers.sums(input=[self.correct, correct], out=self.correct)
        self.metrics.append(acc)

    def eval(self, executor, eval_program=None):
        if eval_program is None:
            eval_program = Program()
        block = eval_program.global_block()
        with program_guard(main_program=eval_program):
            total = layers.cast(_clone_var(block, self.total), 'float32')
            correct = layers.cast(_clone_var(block, self.correct), 'float32')
            out = layers.elementwise_div(x=correct, y=total)
        return np.array(executor.run(eval_program, fetch_list=[out])[0])


class ChunkEvaluator(Evaluator):
    """Streaming chunk F1 (reference evaluator.py ChunkEvaluator):
    accumulates chunk_eval op counts in persistable state and recomputes
    precision/recall/F1 at eval()."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super(ChunkEvaluator, self).__init__('chunk_eval')
        main_program = self.helper.main_program
        if main_program.current_block().idx != 0:
            raise ValueError('You can only invoke Evaluator in root block')

        self.num_infer_chunks = self._create_state(
            dtype='int64', shape=[1], suffix='num_infer_chunks')
        self.num_label_chunks = self._create_state(
            dtype='int64', shape=[1], suffix='num_label_chunks')
        self.num_correct_chunks = self._create_state(
            dtype='int64', shape=[1], suffix='num_correct_chunks')
        (precision, recall, f1_score, num_infer_chunks, num_label_chunks,
         num_correct_chunks) = layers.chunk_eval(
             input=input,
             label=label,
             chunk_scheme=chunk_scheme,
             num_chunk_types=num_chunk_types,
             excluded_chunk_types=excluded_chunk_types)
        layers.sums(input=[self.num_infer_chunks, num_infer_chunks],
                    out=self.num_infer_chunks)
        layers.sums(input=[self.num_label_chunks, num_label_chunks],
                    out=self.num_label_chunks)
        layers.sums(input=[self.num_correct_chunks, num_correct_chunks],
                    out=self.num_correct_chunks)
        self.metrics.extend([precision, recall, f1_score])

    def eval(self, executor, eval_program=None):
        scope = global_scope()
        num_infer = float(np.asarray(
            scope.find_var(self.num_infer_chunks.name).value()).flatten()[0])
        num_label = float(np.asarray(
            scope.find_var(self.num_label_chunks.name).value()).flatten()[0])
        num_correct = float(np.asarray(
            scope.find_var(
                self.num_correct_chunks.name).value()).flatten()[0])
        precision = num_correct / num_infer if num_infer else 0.0
        recall = num_correct / num_label if num_label else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if num_correct else 0.0)
        return np.array([precision, recall, f1], dtype='float32')


def _clone_var(block, var):
    return block.create_var(
        name=var.name,
        shape=var.shape,
        dtype=var.dtype,
        persistable=True)
