"""Program serialization — the program-is-data contract.

The reference serializes ProgramDesc as a protobuf (framework.proto:183);
this build serializes an equivalent structural dict.  Sub-block references
in op attrs become ``{'__block__': idx}`` markers.
"""

import json

import numpy as np

from . import core


def _var_to_dict(v):
    from .framework import Parameter
    return {
        'name': v.name,
        'type': v.type,
        'shape': list(v.shape),
        'dtype': v.dtype,
        'lod_level': v.lod_level,
        'persistable': v.persistable,
        'stop_gradient': v.stop_gradient,
        'is_data': v.is_data,
        'is_parameter': isinstance(v, Parameter),
        'trainable': getattr(v, 'trainable', False),
    }


def _attr_to_serializable(val):
    from .framework import Block
    if isinstance(val, Block):
        return {'__block__': val.idx}
    if isinstance(val, np.ndarray):
        return {'__ndarray__': val.tolist(), '__dtype__': str(val.dtype)}
    if isinstance(val, np.integer):
        return int(val)
    if isinstance(val, np.floating):
        return float(val)
    if isinstance(val, np.bool_):
        return bool(val)
    if isinstance(val, (list, tuple)):
        return [_attr_to_serializable(v) for v in val]
    return val


def _attr_from_serializable(val, program):
    if isinstance(val, dict) and '__block__' in val:
        return program.block(val['__block__'])
    if isinstance(val, dict) and '__ndarray__' in val:
        return np.asarray(val['__ndarray__'], dtype=val['__dtype__'])
    return val


def program_to_dict(program):
    blocks = []
    for blk in program.blocks:
        blocks.append({
            'idx': blk.idx,
            'parent_idx': blk.parent_idx,
            'vars': [_var_to_dict(v) for v in blk.vars.values()],
            'ops': [{
                'type': op.type,
                'inputs': {k: list(v) for k, v in op.inputs.items()},
                'outputs': {k: list(v) for k, v in op.outputs.items()},
                'attrs': {k: _attr_to_serializable(v)
                          for k, v in op.attrs.items()},
            } for op in blk.ops],
        })
    return {'blocks': blocks, 'random_seed': program.random_seed}


def dict_to_program(data):
    from .framework import Program, Block, Variable, Parameter, Operator
    program = Program()
    # make the right number of blocks first (for sub-block attr resolution)
    while len(program.blocks) < len(data['blocks']):
        program.blocks.append(
            Block(program, len(program.blocks),
                  data['blocks'][len(program.blocks)]['parent_idx']))
    program.current_block_idx = 0
    program.random_seed = data.get('random_seed', 0)
    for bdata, blk in zip(data['blocks'], program.blocks):
        blk.parent_idx = bdata['parent_idx']
        for vd in bdata['vars']:
            kwargs = dict(
                type=vd['type'],
                name=vd['name'],
                shape=vd['shape'],
                dtype=vd['dtype'],
                lod_level=vd['lod_level'],
                persistable=vd['persistable'],
                stop_gradient=vd['stop_gradient'],
                is_data=vd['is_data'])
            if vd.get('is_parameter'):
                p = Parameter(blk, shape=vd['shape'], dtype=vd['dtype'],
                              name=vd['name'],
                              persistable=vd['persistable'])
                p.trainable = vd.get('trainable', True)
                p.stop_gradient = vd['stop_gradient']
                blk.vars[p.name] = p
            else:
                v = Variable(blk, **kwargs)
                blk.vars[v.name] = v
        for od in bdata['ops']:
            op = Operator(
                blk,
                od['type'],
                inputs=od['inputs'],
                outputs=od['outputs'],
                attrs={
                    k: _attr_from_serializable(v, program)
                    for k, v in od['attrs'].items()
                })
            blk.ops.append(op)
    program._bump_version()
    return program


def serialize_program(program):
    # JSON, not pickle: loading a model from disk must never execute code
    return json.dumps(program_to_dict(program)).encode('utf-8')


def deserialize_program(data):
    if isinstance(data, bytes):
        data = data.decode('utf-8')
    return dict_to_program(json.loads(data))
