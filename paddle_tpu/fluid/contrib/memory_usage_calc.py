"""Estimate a program's feed-forward memory footprint (reference:
python/paddle/fluid/contrib/memory_usage_calc.py memory_usage).

The reference sums var numel x dtype width over the program with the
batch dim substituted; the same estimate holds here — under XLA the
buffer assignment may alias/reuse more aggressively, so this is the
upper bound the reference also reported.
"""

__all__ = ['memory_usage']

_DTYPE_BYTES = {
    'float16': 2, 'bfloat16': 2, 'float32': 4, 'float64': 8,
    'int8': 1, 'uint8': 1, 'int16': 2, 'int32': 4, 'int64': 8, 'bool': 1,
}


def memory_usage(program, batch_size):
    """Rough bytes used by one forward pass at ``batch_size``.

    Returns (min_estimate, max_estimate, unit_str) like the reference
    (its two bounds bracketed allocator slack; XLA's buffer assignment
    typically lands near the lower bound).
    """
    from ..framework import Program
    if not isinstance(program, Program):
        raise TypeError('memory_usage expects a Program')
    if batch_size <= 0:
        raise ValueError('batch_size must be positive')
    total = 0.0
    for var in program.list_vars():
        shape = getattr(var, 'shape', None)
        if not shape:
            continue
        numel = 1
        for d in shape:
            numel *= batch_size if (d is None or int(d) < 0) else int(d)
        dtype = str(getattr(var, 'dtype', 'float32'))
        total += numel * _DTYPE_BYTES.get(dtype.split('.')[-1], 4)
    low, high = total * 0.9, total * 1.1
    for unit in ('B', 'KB', 'MB', 'GB'):
        if high < 1024 or unit == 'GB':
            return round(low, 2), round(high, 2), unit
        low /= 1024.0
        high /= 1024.0
